"""Goodput/badput accounting: classify every second of a job's wall-time.

The operator's first question about a job on a shared cluster is not "did it
succeed" but "what fraction of its wall-clock was *productive*, and where did
the rest go?" (PAPER.md §0 — accountability is TonY's whole premise; ROADMAP
item 2 needs the answer to aim the MFU work). This module turns the artifacts
the repo already emits — the ``.jhist`` event stream (cluster/events.py) and
the span JSONL trace (obs/trace.py), both resolved through obs/artifacts.py —
into an **exact partition** of ``[t0, t1]`` into phases:

==================  =========================================================
``queue_wait``      queued behind other tenants (QUEUE_WAIT episodes)
``startup``         container allocation + executor launch, per gang epoch
``registration``    the gang registration barrier (first TASK_REGISTERED →
                    GANG_COMPLETE)
``compile``         first-step XLA compile (train.first_step spans when
                    traced, else estimated to the first step evidence)
``productive``      steps actually advancing the job — THE goodput
``checkpoint``      checkpoint save work on the step path (ckpt.save spans)
``input_wait``      step loop blocked on the input pipeline
                    (train.input_wait spans, train/input_pipeline.py)
``restart_rework``  work the job had already done and lost to a restart:
                    the time between the last checkpointed step and the
                    failure, re-derived from the step reports of adjacent
                    gang epochs (the resumed epoch's first step says where
                    the checkpoint was)
``resize``          elastic-resize episodes (GANG_RESIZED → the resized
                    gang's GANG_COMPLETE)
``takeover``        AM journal replay + gang adoption (am.takeover spans)
``drain``           teardown after the last task finished
``other``           anything unattributable (history gaps, torn streams)
==================  =========================================================

Exactness is by construction: claims derived from events/spans are laid over
the integer-millisecond timeline, each elementary interval is assigned to the
single highest-priority covering claim (``productive`` is the filler inside a
live gang window, ``other`` outside), and the phase totals therefore sum to
``t1 - t0`` to the millisecond — property-tested over randomized histories in
tests/test_goodput.py.

Also here: :class:`StragglerDetector` — per-task step-time skew from the
piggybacked ``tony_train_step_seconds`` histograms, flagging ranks whose step
time persistently exceeds the gang median — used by the AM's goodput tick
(cluster/appmaster.py) and fed to ``tony top`` / the portal. The alert-rule
engine that consumes both lives in obs/alerts.py.
"""

from __future__ import annotations

import math
from dataclasses import dataclass, field
from typing import Any, Iterable, Mapping

#: phase names in display order; ``productive`` is the goodput, the rest is
#: the badput breakdown
PHASE_ORDER = (
    "productive", "queue_wait", "startup", "registration", "compile",
    "checkpoint", "input_wait", "restart_rework", "preempt_drain", "resize",
    "takeover", "drain", "other",
)

#: claim priorities: when claims overlap, the highest wins for that instant.
#: takeover/checkpoint/rework are narrow and precise; startup/productive are
#: wide fillers that yield to everything more specific.
_PRIORITY = {
    "takeover": 90,
    "checkpoint": 80,
    # step loop blocked on the input pipeline (train.input_wait spans,
    # train/input_pipeline.py): narrow precise claims like checkpoint —
    # inside a live gang window, badput the operator tunes with
    # tony.train.prefetch-depth rather than "productive" dilution
    "input_wait": 75,
    "restart_rework": 70,
    # cooperative-preemption drain window (PREEMPTION_REQUESTED → YIELDED/
    # ESCALATED): wider than the urgent ckpt.save inside it (which wins),
    # narrower than rework — the window is real badput the operator tunes
    # with tony.pool.preemption.drain-ms, not "other"
    "preempt_drain": 65,
    "queue_wait": 60,
    "compile": 50,
    "registration": 45,
    "resize": 40,
    "startup": 30,
    "drain": 20,
    "productive": 10,
}


@dataclass
class Ledger:
    """The exact phase partition of one job's wall-time (all times int ms)."""

    app_id: str
    t0_ms: int
    t1_ms: int
    live: bool                                   # t1 is "now", not a verdict
    phases_ms: dict[str, int]                    # phase → total milliseconds
    episodes: list[tuple[str, int, int]]         # merged (phase, start, end)
    restarts: int = 0
    resizes: int = 0
    takeovers: int = 0
    step_time_by_task_ms: dict[str, float] = field(default_factory=dict)

    @property
    def wall_ms(self) -> int:
        return max(self.t1_ms - self.t0_ms, 0)

    @property
    def goodput_fraction(self) -> float:
        """productive / wall — THE goodput number."""
        return (self.phases_ms.get("productive", 0) / self.wall_ms
                if self.wall_ms > 0 else 0.0)

    def badput_ms(self) -> dict[str, int]:
        """Non-productive phases with non-zero time, largest first."""
        items = [(p, ms) for p, ms in self.phases_ms.items()
                 if p != "productive" and ms > 0]
        return dict(sorted(items, key=lambda kv: -kv[1]))

    def disruption_fraction(self, phases: tuple[str, ...] = (
            "restart_rework", "preempt_drain", "resize")) -> float:
        """Fraction of wall-time lost to the named disruption phases — the
        capacity-market verdict number: a borrower repeatedly shed and
        regrown pays exactly these (drain windows, restart/resize rebuilds,
        replayed work), so the market e2e bounds this fraction to prove the
        spike's funding did not churn the training gang to death."""
        if self.wall_ms <= 0:
            return 0.0
        return sum(self.phases_ms.get(p, 0) for p in phases) / self.wall_ms

    def window_fraction(self, window_ms: int) -> float:
        """Goodput over the trailing ``window_ms`` (clipped to the job) —
        the value live alert rules evaluate: a cumulative fraction can never
        resolve after one early stall, a windowed one recovers."""
        lo = max(self.t1_ms - int(window_ms), self.t0_ms)
        span = self.t1_ms - lo
        if span <= 0:
            return 0.0
        good = sum(
            min(e, self.t1_ms) - max(s, lo)
            for ph, s, e in self.episodes
            if ph == "productive" and e > lo and s < self.t1_ms
        )
        return max(good, 0) / span

    def skew_by_task(self) -> dict[str, float]:
        """Per-task step-time / gang-median ratio (finalized-job analog of
        the live :class:`StragglerDetector` view)."""
        times = self.step_time_by_task_ms
        if not times:
            return {}
        med = _median(list(times.values()))
        if med <= 0:
            return {}
        return {t: v / med for t, v in sorted(times.items())}

    def to_dict(self) -> dict[str, Any]:
        return {
            "app_id": self.app_id,
            "t0_ms": self.t0_ms,
            "t1_ms": self.t1_ms,
            "live": self.live,
            "wall_ms": self.wall_ms,
            "goodput_fraction": self.goodput_fraction,
            "phases_ms": dict(self.phases_ms),
            "restarts": self.restarts,
            "resizes": self.resizes,
            "takeovers": self.takeovers,
            "step_time_by_task_ms": dict(self.step_time_by_task_ms),
            "skew_by_task": self.skew_by_task(),
        }


def _median(vals: list[float]) -> float:
    if not vals:
        return 0.0
    s = sorted(vals)
    n = len(s)
    return s[n // 2] if n % 2 else (s[n // 2 - 1] + s[n // 2]) / 2.0


def _ev_type(ev: Any) -> str:
    return ev.type.value


def _is_restart_marker(ev: Any) -> bool:
    """HEARTBEAT_LOST doubles as the task-lost event and the gang-restart
    announcement; the restart spelling carries reason='gang restart: …'."""
    return (_ev_type(ev) == "HEARTBEAT_LOST"
            and str(ev.payload.get("reason", "")).startswith("gang restart"))


def _snapshot_steps(ev: Any) -> dict[str, int]:
    """task → train step from one METRICS_SNAPSHOT event."""
    out: dict[str, int] = {}
    for entry in ev.payload.get("tasks", []):
        step = ((entry.get("metrics") or {}).get("train") or {}).get("step")
        if isinstance(step, (int, float)) and math.isfinite(step):
            out[str(entry.get("task", "?"))] = int(step)
    return out


def _span_ms(s: Mapping[str, Any]) -> tuple[int, int]:
    start = int(round(float(s.get("start_ms", 0.0))))
    end = int(round(float(s.get("end_ms", start))))
    return start, max(end, start)


def flagged_stragglers(events: Iterable[Any]) -> list[str]:
    """Ranks whose LAST straggler transition in the event stream is
    ``STRAGGLER_DETECTED`` — the finalized-job answer to "who was dragging
    the gang at the end". Order matters: a rank can resolve across a gang
    restart (its stats vanish) and be re-detected afterwards."""
    state: dict[str, bool] = {}
    for ev in events:
        t = _ev_type(ev)
        if t == "STRAGGLER_DETECTED":
            state[str(ev.payload.get("task"))] = True
        elif t == "STRAGGLER_RESOLVED":
            state[str(ev.payload.get("task"))] = False
    return sorted(task for task, flagged in state.items() if flagged)


def step_time_by_task(events: Iterable[Any]) -> dict[str, float]:
    """Mean per-task step wall time (ms) from METRICS_SNAPSHOT deltas — the
    finalized-job source for per-rank skew (`tony goodput`), mirroring the
    derived ``step_time_ms`` series the history ingester distills."""
    last: dict[str, tuple[int, int]] = {}            # task → (step, ts)
    total: dict[str, float] = {}
    count: dict[str, int] = {}
    for ev in events:
        if _ev_type(ev) == "GANG_COMPLETE":
            # epoch boundary: a delta straddling a restart/resize would
            # charge the whole outage gap to whichever ranks' step counts
            # happened to increase across it
            last.clear()
            continue
        if _ev_type(ev) != "METRICS_SNAPSHOT":
            continue
        for task, step in _snapshot_steps(ev).items():
            prev = last.get(task)
            if prev is not None and step > prev[0] and ev.timestamp_ms > prev[1]:
                total[task] = total.get(task, 0.0) + (ev.timestamp_ms - prev[1])
                count[task] = count.get(task, 0) + (step - prev[0])
            last[task] = (step, ev.timestamp_ms)
    return {t: total[t] / count[t] for t in total if count.get(t)}


def build_ledger(
    app_id: str,
    events: list[Any],
    spans: list[Mapping[str, Any]] | None = None,
    now_ms: int | None = None,
) -> Ledger:
    """The exact phase partition for one job from its event stream (+ spans
    when the job was traced).

    ``events`` is the (possibly torn-truncated) ``.jhist`` stream in file
    order; ``spans`` the merged span dicts (obs/artifacts.load_spans). A job
    without an APPLICATION_FINISHED event is treated as live and accounted
    up to ``now_ms`` (required then).
    """
    spans = spans or []
    if not events:
        now = int(now_ms or 0)
        return Ledger(app_id, now, now, live=True, phases_ms={}, episodes=[])

    t0 = min(ev.timestamp_ms for ev in events)
    finished = [ev for ev in events if _ev_type(ev) == "APPLICATION_FINISHED"]
    if finished:
        t1, live = finished[-1].timestamp_ms, False
    else:
        if now_ms is None:
            raise ValueError("live job: pass now_ms to account up to the present")
        t1, live = int(now_ms), True
    t1 = max(t1, t0)

    claims: list[tuple[int, int, int, str]] = []     # (start, end, prio, phase)

    def claim(phase: str, start: int, end: int) -> None:
        start, end = max(int(start), t0), min(int(end), t1)
        if end > start:
            claims.append((start, end, _PRIORITY[phase], phase))

    # ---- queue wait: waiting → admitted pairs (unterminated waits run to t1)
    wait_start: int | None = None
    for ev in events:
        if _ev_type(ev) != "QUEUE_WAIT":
            continue
        if ev.payload.get("state") == "waiting" and wait_start is None:
            wait_start = ev.timestamp_ms
        elif ev.payload.get("state") == "admitted" and wait_start is not None:
            claim("queue_wait", wait_start, ev.timestamp_ms)
            wait_start = None
    if wait_start is not None:
        claim("queue_wait", wait_start, t1)

    # ---- gang epochs: boundaries are GANG_COMPLETE (epoch start) and the
    # next restart marker / t1 (epoch end); epoch starts are restart markers
    completes = [ev.timestamp_ms for ev in events if _ev_type(ev) == "GANG_COMPLETE"]
    restarts = [ev.timestamp_ms for ev in events if _is_restart_marker(ev)]
    resize_marks = [
        ev.timestamp_ms for ev in events
        if _ev_type(ev) == "GANG_RESIZED" and not ev.payload.get("rejected")
    ]
    takeover_events = [
        ev for ev in events
        if _ev_type(ev) in ("AM_TAKEOVER", "AM_TAKEOVER_DEGRADED")
    ]

    def next_at_or_after(ts_list: list[int], t: int, default: int) -> int:
        """First timestamp >= t (inclusive: an epoch's GANG_COMPLETE can
        land in the same millisecond as the epoch start — the claim must
        then be empty, not span the rest of the job)."""
        later = [x for x in ts_list if x >= t]
        return min(later) if later else default

    # startup: [epoch start, its GANG_COMPLETE] — epoch starts are t0 and
    # every restart marker; a gang that never completes claims to epoch end
    for start in [t0] + restarts:
        claim("startup", start, next_at_or_after(completes, start, t1))

    # registration barrier: first TASK_REGISTERED of the epoch → GANG_COMPLETE
    regs = [ev.timestamp_ms for ev in events if _ev_type(ev) == "TASK_REGISTERED"]
    for start in [t0] + restarts:
        gc = next_at_or_after(completes, start, t1)
        first_reg = next_at_or_after(regs, start, gc)
        if first_reg < gc:
            claim("registration", first_reg, gc)

    # productive filler: [GANG_COMPLETE, next restart marker / t1]; the
    # marker search starts just past gc so the restart that CAUSED this
    # epoch (always <= gc) is never taken as its end
    for gc in completes:
        claim("productive", gc, next_at_or_after(restarts, gc + 1, t1))

    # resize episodes: the resize announcement through the resized gang's
    # completion — wins over generic startup, yields to registration/compile
    for rm in resize_marks:
        claim("resize", rm, next_at_or_after(completes, rm + 1, t1))

    # ---- compile: traced first-step spans, else first step evidence
    first_steps = [s for s in spans if s.get("name") == "train.first_step"]
    snapshots = [ev for ev in events if _ev_type(ev) == "METRICS_SNAPSHOT"]
    for gc in completes:
        epoch_end = next_at_or_after(restarts, gc + 1, t1)
        ends = [
            _span_ms(s)[1] for s in first_steps
            if gc <= _span_ms(s)[0] < epoch_end
        ]
        if ends:
            claim("compile", gc, min(max(ends), epoch_end))
            continue
        for ev in snapshots:
            if ev.timestamp_ms <= gc or ev.timestamp_ms >= epoch_end:
                continue
            if any(v >= 1 for v in _snapshot_steps(ev).values()):
                claim("compile", gc, ev.timestamp_ms)
                break

    # ---- checkpoint: save spans (the restore cost after a restart is
    # already inside startup/resize; double-claiming it would shrink them)
    for s in spans:
        if s.get("name") == "ckpt.save":
            start, end = _span_ms(s)
            claim("checkpoint", start, end)

    # ---- input wait: step-loop stalls on the input pipeline (backdated
    # spans the prefetcher emits for waits past its span floor; sub-floor
    # waits stay inside productive — they are noise, not a phase)
    for s in spans:
        if s.get("name") == "train.input_wait":
            start, end = _span_ms(s)
            claim("input_wait", start, end)

    # ---- takeover: journal replay + adoption (traced); without a span the
    # event is an instant and contributes no width
    for s in spans:
        if s.get("name") == "am.takeover":
            start, end = _span_ms(s)
            claim("takeover", start, end)

    # ---- restart rework: for each restart, the resumed epoch's first step
    # report says where the checkpoint was; everything the previous epoch
    # ran past that step was lost and re-done
    epoch_steps: list[list[tuple[int, int]]] = [[] for _ in range(len(completes) + 1)]
    for ev in snapshots:
        # snapshot belongs to the epoch of the last GANG_COMPLETE before it
        epoch = sum(1 for gc in completes if gc <= ev.timestamp_ms)
        steps = _snapshot_steps(ev)
        if steps:
            epoch_steps[epoch].append((ev.timestamp_ms, max(steps.values())))
    for rt in restarts:
        prev_epoch = sum(1 for gc in completes if gc <= rt)
        next_epoch = prev_epoch + 1
        if prev_epoch < 1 or next_epoch >= len(epoch_steps) or not epoch_steps[next_epoch]:
            continue
        resume_step = epoch_steps[next_epoch][0][1]
        lost_from = next(
            (ts for ts, step in epoch_steps[prev_epoch] if step >= resume_step),
            None,
        )
        if lost_from is not None and lost_from < rt:
            claim("restart_rework", lost_from, rt)

    # ---- cooperative-preemption drain windows: request → yield/escalate
    # (an unterminated window ends at the next restart marker — the yield IS
    # the restart — or t1 for a live job mid-drain)
    drain_resolutions = [
        ev.timestamp_ms for ev in events
        if _ev_type(ev) in (
            "PREEMPTION_YIELDED", "PREEMPTION_ESCALATED", "PREEMPTION_CANCELLED")
    ]
    for ev in events:
        if _ev_type(ev) != "PREEMPTION_REQUESTED":
            continue
        end = next_at_or_after(
            drain_resolutions, ev.timestamp_ms,
            next_at_or_after(restarts, ev.timestamp_ms, t1),
        )
        claim("preempt_drain", ev.timestamp_ms, end)

    # ---- drain: after the last evidence of work — the last task finish, or
    # the last metrics snapshot when one outlives it (the final task's
    # finish event can be lost to the shutdown race / a torn tail, and its
    # last productive stretch must not be misread as teardown)
    finishes = [ev.timestamp_ms for ev in events if _ev_type(ev) == "TASK_FINISHED"]
    if not live and finishes:
        claim("drain", max(finishes + [ev.timestamp_ms for ev in snapshots]), t1)

    phases_ms, episodes = _partition(t0, t1, claims)
    return Ledger(
        app_id=app_id,
        t0_ms=t0,
        t1_ms=t1,
        live=live,
        phases_ms=phases_ms,
        episodes=episodes,
        restarts=len(restarts),
        resizes=len(resize_marks),
        takeovers=len(takeover_events),
        step_time_by_task_ms=step_time_by_task(events),
    )


def _partition(
    t0: int, t1: int, claims: list[tuple[int, int, int, str]]
) -> tuple[dict[str, int], list[tuple[str, int, int]]]:
    """Sweep the claim edges: each elementary interval goes to the single
    highest-priority covering claim (ties broken by later claim — irrelevant,
    same phase priorities are unique), else ``other``. Integer milliseconds
    throughout, so the phase totals sum to ``t1 - t0`` EXACTLY."""
    bounds = sorted({t0, t1, *(c[0] for c in claims), *(c[1] for c in claims)})
    bounds = [b for b in bounds if t0 <= b <= t1]
    phases: dict[str, int] = {}
    episodes: list[tuple[str, int, int]] = []
    for lo, hi in zip(bounds, bounds[1:]):
        if hi <= lo:
            continue
        best = None
        for start, end, prio, phase in claims:
            if start <= lo and end >= hi and (best is None or prio > best[0]):
                best = (prio, phase)
        phase = best[1] if best else "other"
        phases[phase] = phases.get(phase, 0) + (hi - lo)
        if episodes and episodes[-1][0] == phase and episodes[-1][2] == lo:
            episodes[-1] = (phase, episodes[-1][1], hi)
        else:
            episodes.append((phase, lo, hi))
    return phases, episodes


class JhistFollower:
    """Incremental reader of one append-only ``.jhist``: each :meth:`poll`
    parses only the bytes appended since the last call (complete lines
    only — a torn tail waits for its newline) and returns the accumulated
    event list. The AM's goodput tick and ``get_goodput`` RPC share one
    instance, so a long job pays O(new events) per tick for file I/O + JSON
    instead of re-reading its whole history every few seconds. Thread-safe:
    RPC handler threads race the monitor loop on it."""

    def __init__(self, path: str):
        self.path = path
        self._pos = 0
        self._events: list[Any] = []
        import threading

        self._lock = threading.Lock()

    def poll(self) -> list[Any]:
        from tony_tpu.cluster.events import Event

        with self._lock:
            try:
                with open(self.path, "rb") as f:  # lint: disable=blocking-under-lock — leaf lock serializing the follower's (pos, tail-buffer) against concurrent polls; local jhist read
                    f.seek(self._pos)
                    chunk = f.read()
            except OSError:
                return list(self._events)
            end = chunk.rfind(b"\n")
            if end >= 0:
                for line in chunk[:end].split(b"\n"):
                    if not line.strip():
                        continue
                    try:
                        self._events.append(
                            Event.from_json(line.decode("utf-8", "replace")))
                    except (ValueError, AttributeError, TypeError):
                        continue  # garbled line: live accounting skips it
                self._pos += end + 1
            return list(self._events)


def build_ledger_from_artifacts(art, now_ms: int | None = None) -> Ledger:
    """Ledger straight off the artifact index (finalized or live job):
    events with torn tolerance + spans when traced. The single resolution
    `tony goodput`, the portal, the history ingester, and the AM's live
    tick all share."""
    from tony_tpu.obs import artifacts as obs_artifacts

    events, _complete = art.read_events()
    spans = obs_artifacts.load_spans(art.trace_dir)
    return build_ledger(art.app_id, events, spans, now_ms=now_ms)


# ---------------------------------------------------------------------------
# straggler detection: per-task step-time skew off the piggybacked histograms
# ---------------------------------------------------------------------------
def histogram_percentile(
    snapshots: Iterable[Any], name: str, q: float
) -> float | None:
    """Upper-bound percentile estimate over the merged bucket counts of one
    histogram across many registry snapshots (the per-task groups of the
    AM's ``get_metrics``): the q-quantile's bucket upper edge, in the
    histogram's native unit. None without samples."""
    buckets: list[float] | None = None
    counts: list[int] | None = None
    total = 0
    for snap in snapshots:
        for m in snap or []:
            if m.get("name") != name or m.get("type") != "histogram":
                continue
            bs = list(m.get("buckets") or [])
            for sample in m.get("samples", []):
                cs = list(sample.get("counts") or [])
                if buckets is None:
                    buckets, counts = bs, [0] * len(cs)
                if bs != buckets or len(cs) != len(counts):
                    continue  # shape drift between processes: skip, not lie
                counts = [a + b for a, b in zip(counts, cs)]
                total += int(sample.get("count", 0))
    if not total or buckets is None or counts is None:
        return None
    target = q * total
    cum = 0
    for i, n in enumerate(counts[:-1]):
        cum += n
        if cum >= target:
            return float(buckets[i])
    return float(buckets[-1])  # overflow bucket: report the largest edge


class StragglerDetector:
    """Flags ranks whose step time persistently exceeds the gang median.

    Fed once per goodput tick with the per-task cumulative ``(count, sum)``
    of ``tony_train_step_seconds`` (obs_introspect.step_stats_by_task); the
    delta between ticks is the task's live step time. A task whose
    time >= ``factor`` × the gang median for ``min_checks`` consecutive
    *evaluated* ticks is a straggler until it drops back under — the
    transitions come back as ``("detected"|"resolved", task, ratio,
    median_s)`` tuples for the caller to turn into events/gauges. A rank
    that stops advancing entirely — the worst straggler — is judged by the
    time since its last completed step (a LOWER bound on its in-flight step
    time) once that bound alone crosses the factor. Needs 3+ participating
    tasks: with two, "the median" is the midpoint of the pair and a slow
    rank drags it.
    """

    def __init__(self, factor: float = 1.5, min_checks: int = 3):
        self.factor = max(float(factor), 1.0)
        self.min_checks = max(int(min_checks), 1)
        self._prev: dict[str, tuple[int, float]] = {}
        self._last_advance: dict[str, float] = {}   # task → monotonic seconds
        self._streak: dict[str, int] = {}
        self.flagged: set[str] = set()
        self.skew: dict[str, float] = {}
        self.median_s: float = 0.0

    def observe(
        self, stats: Mapping[str, tuple[int, float]], now_s: float | None = None
    ) -> list[tuple[str, str, float, float]]:
        """One tick. Returns state transitions (see class docstring)."""
        import time as _time

        now = _time.monotonic() if now_s is None else now_s
        times: dict[str, float] = {}
        stalled: dict[str, float] = {}   # no new steps → lower-bound step time
        for task, (count, total) in stats.items():
            prev = self._prev.get(task)
            self._prev[task] = (count, total)
            if prev is None:
                self._last_advance[task] = now
            elif count > prev[0] and total > prev[1]:
                times[task] = (total - prev[1]) / (count - prev[0])
                self._last_advance[task] = now
            else:
                stalled[task] = now - self._last_advance.get(task, now)
        # tasks that vanished (resized away, finished) resolve silently
        gone = set(self._prev) - set(stats)
        out: list[tuple[str, str, float, float]] = []
        for task in sorted(gone):
            self._prev.pop(task, None)
            self._last_advance.pop(task, None)
            self._streak.pop(task, None)
            self.skew.pop(task, None)
            if task in self.flagged:
                self.flagged.discard(task)
                out.append(("resolved", task, 0.0, self.median_s))
        if len(times) < 2 or len(times) + len(stalled) < 3:
            return out
        med = _median(list(times.values()))
        if med <= 0:
            return out
        self.median_s = med
        # a stalled rank joins the evaluation once its silence ALONE exceeds
        # the factor (its true step time can only be longer); a rank merely
        # mid-step (bound under the factor) holds its streak/skew unchanged
        judged = dict(times)
        for task, bound in stalled.items():
            if bound / med >= self.factor:
                judged[task] = bound
        for task, t in sorted(judged.items()):
            ratio = t / med
            self.skew[task] = ratio
            if ratio >= self.factor:
                self._streak[task] = self._streak.get(task, 0) + 1
                if self._streak[task] >= self.min_checks and task not in self.flagged:
                    self.flagged.add(task)
                    out.append(("detected", task, ratio, med))
            else:
                self._streak[task] = 0
                if task in self.flagged:
                    self.flagged.discard(task)
                    out.append(("resolved", task, ratio, med))
        return out
