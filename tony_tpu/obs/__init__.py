"""Observability: distributed tracing (trace.py), metrics registry
(metrics.py), structured logging (logging.py), and the live-introspection
plane behind ``tony profile`` / ``tony top`` (introspect.py).

Docs: docs/observability.md. Disabled tracing (the default) costs one None
check per hook; metrics recording is gated by ``tony.metrics.enabled``;
log records below ``tony.log.level`` are never built.
"""

from tony_tpu.obs import introspect, logging, metrics, trace
from tony_tpu.obs.introspect import AlreadyProfilingError
from tony_tpu.obs.logging import JsonLogger
from tony_tpu.obs.metrics import REGISTRY, MetricsRegistry, render_merged
from tony_tpu.obs.trace import Span, Tracer

__all__ = [
    "introspect",
    "logging",
    "metrics",
    "trace",
    "AlreadyProfilingError",
    "JsonLogger",
    "REGISTRY",
    "MetricsRegistry",
    "render_merged",
    "Span",
    "Tracer",
]
