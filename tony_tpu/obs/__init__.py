"""Observability: distributed tracing (trace.py) + metrics registry (metrics.py).

Docs: docs/observability.md. Disabled tracing (the default) costs one None
check per hook; metrics recording is gated by ``tony.metrics.enabled``.
"""

from tony_tpu.obs import metrics, trace
from tony_tpu.obs.metrics import REGISTRY, MetricsRegistry, render_merged
from tony_tpu.obs.trace import Span, Tracer

__all__ = [
    "metrics",
    "trace",
    "REGISTRY",
    "MetricsRegistry",
    "render_merged",
    "Span",
    "Tracer",
]
