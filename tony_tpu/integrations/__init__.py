"""Workflow-scheduler integrations (the reference's ``tony-azkaban`` analog)."""

from tony_tpu.integrations.workflow import TonyWorkflowJob, run_workflow_job

__all__ = ["TonyWorkflowJob", "run_workflow_job"]
