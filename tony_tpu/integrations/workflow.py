"""Workflow-scheduler jobtype: run a tony job as one step of a DAG engine.

Analog of the reference's ``tony-azkaban`` plugin (``TonyJob`` extending the
Hadoop java jobtype — SURVEY.md §2.3): a workflow engine hands the jobtype a
flat properties map; the jobtype merges those properties into ``tony.*``
configuration with the same precedence the reference uses (explicit ``tony.*``
props win over convenience shorthands), then submits through the normal client
and reports the job's exit status back to the engine.

Engine-agnostic on purpose: Azkaban/Airflow/Oozie all reduce to "flat props in,
exit code out". An Airflow user wraps ``run_workflow_job`` in a PythonOperator;
an Azkaban-style engine shells out to ``python -m tony_tpu.integrations.workflow``.
"""

from __future__ import annotations

import json
import sys

from tony_tpu.config import TonyConfig, keys
from tony_tpu.obs import logging as obs_logging

# Convenience shorthands a workflow step may use instead of full tony.* keys
# (reference TonyJob maps Azkaban's job props the same way).
_SHORTHANDS = {
    "executes": keys.EXECUTES,
    "command": keys.EXECUTES,
    "src_dir": keys.SRC_DIR,
    "python_venv": keys.PYTHON_VENV,
    "python_binary_path": keys.PYTHON_BINARY_PATH,
    "shell_env": keys.SHELL_ENV,
    "staging_root": keys.STAGING_ROOT,
    "queue": keys.APPLICATION_QUEUE,
}


class TonyWorkflowJob:
    """One workflow step that submits a tony job (TonyJob analog)."""

    def __init__(self, name: str, props: dict[str, str]):
        self.name = name
        self.props = dict(props)

    def build_config(self) -> TonyConfig:
        """Merge workflow props → layered tony config.

        Order (later wins, mirroring the reference's Props resolution):
        defaults ← conf_file prop ← shorthand props ← explicit ``tony.*`` props.
        """
        config = TonyConfig.from_layers(conf_file=self.props.get("conf_file"))
        config.set(keys.APPLICATION_NAME, self.name)  # step name; overridable below
        for prop, key in _SHORTHANDS.items():
            if prop in self.props:
                config.set(key, self.props[prop])
        for prop, value in self.props.items():
            if prop.startswith("tony."):
                config.set(prop, value)
        return config

    def run(self) -> int:
        """Submit and monitor; the exit code is the workflow step's verdict."""
        from tony_tpu.cluster.client import Client

        return Client(self.build_config()).run(quiet=False)


def run_workflow_job(name: str, props: dict[str, str]) -> int:
    """Functional entry point for PythonOperator-style engines."""
    return TonyWorkflowJob(name, props).run()


def main(argv: list[str] | None = None) -> int:
    """Shell entry point: ``python -m tony_tpu.integrations.workflow <name> <props.json>``
    (props.json: flat string map, the engine's rendered step properties)."""
    argv = list(sys.argv[1:] if argv is None else argv)
    if len(argv) != 2:
        obs_logging.error("usage: python -m tony_tpu.integrations.workflow <job-name> <props.json>")
        return 2
    with open(argv[1]) as f:
        props = {str(k): str(v) for k, v in json.load(f).items()}
    return run_workflow_job(argv[0], props)


if __name__ == "__main__":
    sys.exit(main())
