"""``tony bench --gate``: the perf trajectory as an enforced contract.

The repo accumulates one ``BENCH_<round>.json`` per benchmarked round — a
wrapper ``{"n": <round>, "rc": <exit>, "parsed": {<one bench.py JSON line>}}``
whose ``parsed`` record carries the headline metric (``value``, MFU),
throughput (``tokens_per_sec``), step time, and the kernel-smoke verdict.
Until now that trajectory was advisory; the gate makes it fail-stop:

- :func:`validate_record` — the gate schema every checked-in ``BENCH_*``
  must satisfy (asserted tier-1 by tests/test_bench_gate.py);
- :func:`evaluate` — diff a current record against the trajectory's best
  per metric with per-metric thresholds; a drop beyond threshold (or a
  kernel-smoke failure) is a regression and the CLI exits nonzero.

Direction matters: ``value``/``tokens_per_sec`` regress downward,
``step_time_ms`` regresses upward. The reference point is the trajectory's
BEST, not its latest — a slow round must not ratchet the contract down.
"""

from __future__ import annotations

import glob
import json
import math
import os
from dataclasses import dataclass, field
from typing import Any

#: gated metrics → direction (+1 higher-is-better, -1 lower-is-better).
#: Families share this table: a record only gates the metrics it carries
#: (the train bench has step_time_ms, the SERVE_BENCH family has the TTFT
#: tails) and trajectories never cross metric names, so adding a family
#: means adding its headline directions here, nothing else.
GATE_METRICS: dict[str, int] = {
    "value": +1,            # the headline metric (MFU / serve tokens/s / cbench ops/s)
    "vs_baseline": +1,
    "tokens_per_sec": +1,
    "step_time_ms": -1,
    "ttft_p99_ms": -1,      # SERVE_BENCH: tail time-to-first-token
    "ttft_p95_ms": -1,
    # SERVE_BENCH disagg lane (serve/disagg.py): the prefill→decode KV
    # handoff's median wall time regresses upward — a slow handoff eats the
    # TTFT win disaggregation exists for
    "handoff_p50_ms": -1,
    # SERVE_BENCH SLO lane (tony loadtest + obs/slo.py): the share of the
    # error budget the run burned regresses upward; the verdict itself is a
    # must-be-PASS contract below (same discipline as kernel_smoke)
    "budget_burned_pct": -1,
    # CBENCH family (tony cbench, docs/performance.md "Control-plane
    # scalability"): the five control-plane throughputs regress downward,
    # their latency tails and the restart-replay wall regress upward.
    "sched_decisions_per_sec": +1,
    "sched_decision_p99_ms": -1,
    # steady-state scheduler sub-bench (PR 14): repeated passes over a
    # delta-fed WorldIndex — the cross-pass O(changed) win, gated so it
    # can't silently regress back to rebuild-the-world-per-tick
    "sched_incremental_p50_ms": -1,
    "sched_incremental_passes_per_sec": +1,
    "heartbeats_per_sec": +1,
    "heartbeat_p99_ms": -1,
    "heartbeat_churn_p99_ms": -1,
    "journal_replay_ms": -1,
    "journal_records_per_sec": +1,
    "sweep_jobs_per_sec": +1,
    "resweep_ms": -1,
    "portal_scrape_ms": -1,
    "portal_rescrape_ms": -1,
    "portal_ams_per_sec": +1,
}

#: default allowed drop, percent of the trajectory's best
DEFAULT_TOLERANCE_PCT = 5.0

#: per-metric default thresholds for metrics that are structurally noisier
#: than a headline mean — microbenchmark latency TAILS (a p99 over ~25
#: seeded passes is nearly a max) and short-window throughputs wobble well
#: past 5% between identical runs on shared CI hardware. The bands are
#: still tight enough to catch the regressions that matter (a compaction
#: regression multiplies journal_replay_ms, not +50%). CLI ``--threshold``
#: and an explicit ``--tolerance-pct`` both win over these; the headline
#: ``value`` keeps the strict 5%.
DEFAULT_METRIC_TOLERANCE_PCT: dict[str, float] = {
    "sched_decisions_per_sec": 20.0,
    "sched_decision_p99_ms": 50.0,
    # sub-millisecond medians over 100 passes: scheduler-noise dominated,
    # but a regression to world-rebuild-per-tick is a ~100x move, not 50%
    "sched_incremental_p50_ms": 50.0,
    "sched_incremental_passes_per_sec": 25.0,
    "heartbeats_per_sec": 20.0,
    "heartbeat_p99_ms": 50.0,
    "heartbeat_churn_p99_ms": 50.0,
    "journal_replay_ms": 50.0,
    "journal_records_per_sec": 30.0,
    "sweep_jobs_per_sec": 15.0,
    "resweep_ms": 30.0,
    "portal_scrape_ms": 30.0,
    "portal_rescrape_ms": 50.0,
    "portal_ams_per_sec": 30.0,
}

#: relative headline-metric delta below which a round "didn't move" vs the
#: prior round (the anti-gate-without-movement warning)
MOVEMENT_EPSILON = 0.001

_REQUIRED_PARSED = ("metric", "value", "unit", "vs_baseline")


def parsed_of(record: dict[str, Any]) -> dict[str, Any]:
    """The bench line inside a BENCH wrapper, or the record itself when it
    already IS a raw ``bench.py`` output line."""
    inner = record.get("parsed")
    return inner if isinstance(inner, dict) else record


def machine_of(parsed: dict[str, Any]) -> tuple | None:
    """The record's machine fingerprint (None when it carries none).

    CPU-bound throughput rounds are only comparable on equal hardware: a
    CI reallocation from 8 cores to 2 halves every control-plane lane with
    zero code change, and gating across that boundary reports fiction in
    both directions. The fingerprint is deliberately coarse — core count +
    ISA, not the kernel build string — so routine image patches don't
    orphan a trajectory. Records WITHOUT a fingerprint compare with each
    other (the pre-provenance trajectory stays self-consistent) but not
    with fingerprinted ones — we cannot know they were the same box."""
    m = parsed.get("machine")
    if not isinstance(m, dict):
        return None
    return (m.get("cpus"), m.get("arch"))


def validate_record(record: dict[str, Any], *, wrapper: bool = True) -> list[str]:
    """Gate-schema errors for one record (empty = valid).

    ``wrapper=True`` additionally checks the BENCH_* file shape (round
    number ``n``, exit code ``rc``).
    """
    errors: list[str] = []
    if not isinstance(record, dict):
        return ["record is not a JSON object"]
    if wrapper:
        if not isinstance(record.get("n"), int):
            errors.append("missing/odd round number 'n'")
        if record.get("rc") not in (0,):
            errors.append(f"bench run exit code rc={record.get('rc')!r} (want 0)")
        if not isinstance(record.get("parsed"), dict):
            errors.append("missing 'parsed' bench line")
            return errors
    p = parsed_of(record)
    for key in _REQUIRED_PARSED:
        if key not in p:
            errors.append(f"parsed record missing {key!r}")
    for key in ("value", "vs_baseline"):
        v = p.get(key)
        if key in p and not (isinstance(v, (int, float)) and math.isfinite(v)):
            errors.append(f"parsed {key!r} is not a finite number: {v!r}")
    if not isinstance(p.get("metric", ""), str):
        errors.append("parsed 'metric' is not a string")
    smoke = p.get("kernel_smoke")
    if smoke is not None and smoke_fraction(smoke) is None:
        errors.append(f"kernel_smoke not 'passed/total': {smoke!r}")
    sv = p.get("slo_verdict")
    if sv is not None and str(sv) not in ("PASS", "FAIL", "NO_DATA"):
        errors.append(f"slo_verdict not PASS/FAIL/NO_DATA: {sv!r}")
    return errors


def smoke_fraction(smoke: Any) -> float | None:
    """``"8/8"`` → 1.0; None when unparseable."""
    try:
        passed, _, total = str(smoke).partition("/")
        t = int(total)
        return int(passed) / t if t > 0 else None
    except (ValueError, ZeroDivisionError):
        return None


def load_trajectory(directory: str, pattern: str = "BENCH_*.json") -> list[tuple[str, dict[str, Any]]]:
    """Checked-in trajectory records, ordered by round number: ``(filename,
    wrapper_record)`` pairs. Unreadable files raise — a corrupt trajectory
    is a gate failure, not something to silently skip."""
    out: list[tuple[str, dict[str, Any]]] = []
    for path in sorted(glob.glob(os.path.join(directory, pattern))):
        with open(path) as f:
            out.append((os.path.basename(path), json.load(f)))
    out.sort(key=lambda e: (e[1].get("n") if isinstance(e[1].get("n"), int) else 0, e[0]))
    return out


@dataclass
class GateCheck:
    metric: str
    current: float | None
    reference: float | None
    reference_from: str
    threshold_pct: float
    direction: int
    passed: bool
    note: str = ""


@dataclass
class GateResult:
    passed: bool
    checks: list[GateCheck] = field(default_factory=list)

    def render(self) -> str:
        lines = []
        for c in self.checks:
            verdict = "ok  " if c.passed else "FAIL"
            cur = "-" if c.current is None else f"{c.current:.6g}"
            ref = "-" if c.reference is None else f"{c.reference:.6g}"
            arrow = "↑" if c.direction > 0 else "↓"
            lines.append(
                f"  [{verdict}] {c.metric:<16s} current={cur:<12s} "
                f"best={ref:<12s} ({c.reference_from}) "
                f"tol={c.threshold_pct:.1f}% {arrow}"
                + (f"  {c.note}" if c.note else ""))
        lines.append("gate: " + ("PASS" if self.passed else "REGRESSION"))
        return "\n".join(lines)


def evaluate(
    current: dict[str, Any],
    trajectory: list[tuple[str, dict[str, Any]]],
    tolerance_pct: float | None = None,
    per_metric_pct: dict[str, float] | None = None,
) -> GateResult:
    """Diff ``current`` (wrapper or raw bench line) against the trajectory.

    A metric regresses when it moves against its direction by more than its
    threshold relative to the trajectory's best; metrics absent from either
    side are skipped (a CPU-distilled record has no kernel smoke, an old
    round has no step_time). Comparisons only happen within the same
    headline ``metric`` name — a preset change starts a fresh trajectory —
    and, for records carrying ``machine`` provenance, within the same
    hardware fingerprint (:func:`machine_of`): a round measured on a
    different CPU allocation is surfaced as a note, never used as a
    regression reference.

    Threshold resolution, strongest first: ``per_metric_pct`` (the CLI's
    repeatable ``--threshold METRIC=PCT``), then an explicit
    ``tolerance_pct`` (``--tolerance-pct`` applies to EVERY metric — a
    caller tightening the gate to 1% means 1%, not "1% except where a
    built-in band is looser"), then :data:`DEFAULT_METRIC_TOLERANCE_PCT`,
    then :data:`DEFAULT_TOLERANCE_PCT`.
    """
    per_metric_pct = per_metric_pct or {}
    cur = parsed_of(current)
    cur_name = cur.get("metric")
    cur_machine = machine_of(cur)
    peers = []
    skipped_machines: list[str] = []
    for fname, rec in trajectory:
        p = parsed_of(rec)
        if p.get("metric") != cur_name:
            continue
        # self-comparison guard: gating the newest checked-in record against
        # the trajectory must diff it against the OTHERS
        if p is cur or p == cur:
            continue
        if machine_of(p) != cur_machine:
            # different (or unknown-vs-known) hardware: not a regression
            # reference — surfaced below, never silently dropped
            skipped_machines.append(fname)
            continue
        peers.append((fname, p))
    checks: list[GateCheck] = []

    for metric, direction in GATE_METRICS.items():
        cv = cur.get(metric)
        if not isinstance(cv, (int, float)) or not math.isfinite(cv):
            continue
        best: float | None = None
        best_from = "-"
        for fname, p in peers:
            v = p.get(metric)
            if not isinstance(v, (int, float)) or not math.isfinite(v):
                continue
            if best is None or (direction > 0 and v > best) or (direction < 0 and v < best):
                best, best_from = float(v), fname
        if best is None:
            continue  # nothing comparable in the trajectory
        pct = per_metric_pct.get(metric)
        if pct is None:
            pct = (tolerance_pct if tolerance_pct is not None
                   else DEFAULT_METRIC_TOLERANCE_PCT.get(metric, DEFAULT_TOLERANCE_PCT))
        allowed = abs(best) * pct / 100.0
        drop = (best - cv) if direction > 0 else (cv - best)
        checks.append(GateCheck(
            metric=metric, current=float(cv), reference=best,
            reference_from=best_from, threshold_pct=pct, direction=direction,
            passed=drop <= allowed,
            note="" if drop <= allowed else
            f"regressed {drop / abs(best) * 100.0:.2f}% past the {pct:.1f}% threshold"))

    if skipped_machines:
        cpus = cur_machine[0] if cur_machine else "?"
        checks.append(GateCheck(
            metric="provenance", current=None, reference=None,
            reference_from="-", threshold_pct=0.0, direction=+1, passed=True,
            note=f"NOTE: {len(skipped_machines)} record(s) measured on "
                 f"different hardware not used as regression references "
                 f"({', '.join(skipped_machines[:4])}; this record: "
                 f"{cpus} cpus) — same-machine rounds gate normally"))

    # anti-"gate-without-movement" (ROADMAP item 2): a perf-lane round that
    # gates green with the headline metric sitting exactly where the prior
    # round left it is a no-op round — warn loudly (non-failing: an infra
    # round may legitimately hold the line, but it must be a visible choice).
    prior = peers[-1] if peers else None
    cv = cur.get("value")
    # a trajectory record whose parsed content EQUALS the current record is
    # the canonical no-movement offense (a copied round) — the peers filter
    # above drops it as a self-comparison, which would otherwise silently
    # defeat this very check, so detect it by content first
    dup = next(
        (fname for fname, rec in trajectory
         if parsed_of(rec) is not cur and parsed_of(rec) == cur), None)
    if dup is not None and isinstance(cv, (int, float)) and math.isfinite(cv):
        checks.append(GateCheck(
            metric="movement", current=float(cv), reference=float(cv),
            reference_from=dup, threshold_pct=MOVEMENT_EPSILON * 100,
            direction=+1, passed=True,
            note=f"WARNING: record is content-identical to {dup} — "
                 "gate-without-movement (perf rounds must move the number "
                 "or say why not)"))
    elif prior is not None and isinstance(cv, (int, float)) and math.isfinite(cv):
        pv = prior[1].get("value")
        if (isinstance(pv, (int, float)) and math.isfinite(pv) and pv != 0
                and abs(cv - pv) / abs(pv) < MOVEMENT_EPSILON):
            checks.append(GateCheck(
                metric="movement", current=float(cv), reference=float(pv),
                reference_from=prior[0], threshold_pct=MOVEMENT_EPSILON * 100,
                direction=+1, passed=True,
                note="WARNING: headline metric unchanged vs the prior round "
                     "— gate-without-movement (perf rounds must move the "
                     "number or say why not)"))

    # perf provenance: a perf-lane record should carry its before/after
    # profile artifact references (bench.py --profile-dir captures them)
    if "kernel_smoke" in cur and "profile" not in cur:
        checks.append(GateCheck(
            metric="provenance", current=None, reference=None,
            reference_from="-", threshold_pct=0.0, direction=+1, passed=True,
            note="WARNING: no 'profile' artifact reference in the record — "
                 "perf rounds attach before/after captures "
                 "(bench.py records them by default)"))
    # cbench provenance (same discipline for the control-plane family): a
    # record carrying the per-benchmark metrics without the sizes it ran at
    # cannot be compared against its trajectory — 10k queued apps and 100
    # are different benchmarks wearing the same name
    if any(k in cur for k in ("sched_decisions_per_sec", "journal_replay_ms")) \
            and not isinstance(cur.get("sizes"), dict):
        checks.append(GateCheck(
            metric="provenance", current=None, reference=None,
            reference_from="-", threshold_pct=0.0, direction=+1, passed=True,
            note="WARNING: no 'sizes' block in the cbench record — rounds "
                 "must carry the tony.cbench.* scale they measured at "
                 "(tony cbench records it by default)"))

    # SLO verdict contract (SERVE_BENCH family): a record carrying an SLO
    # verdict must carry PASS — same must-hold shape as kernel_smoke, with
    # NO_DATA failing too (a loadtest that produced no windows measured
    # nothing and must not gate green)
    sv = cur.get("slo_verdict")
    if sv is not None:
        ok = str(sv) == "PASS"
        checks.append(GateCheck(
            metric="slo_verdict", current=1.0 if ok else 0.0, reference=1.0,
            reference_from="contract", threshold_pct=0.0, direction=+1,
            passed=ok,
            note="" if ok else f"SLO verdict {sv!r} (contract: PASS)"))

    frac = smoke_fraction(cur.get("kernel_smoke")) if "kernel_smoke" in cur else None
    if frac is not None:
        checks.append(GateCheck(
            metric="kernel_smoke", current=frac, reference=1.0,
            reference_from="contract", threshold_pct=0.0, direction=+1,
            passed=frac >= 1.0,
            note="" if frac >= 1.0 else "on-chip kernel smoke failures"))

    if not any(c.metric in GATE_METRICS for c in checks):
        # a fresh trajectory (first-ever record, or a preset change that
        # renamed the headline metric) has no reference to regress against:
        # that is a pass-with-note, not a failure — the record already
        # passed the gate schema, and it BECOMES the trajectory to beat
        checks.append(GateCheck(
            metric=cur_name or "?", current=None, reference=None,
            reference_from="-",
            threshold_pct=(DEFAULT_TOLERANCE_PCT if tolerance_pct is None
                           else tolerance_pct),
            direction=+1, passed=True,
            note="no comparable trajectory records — fresh trajectory, nothing to diff"))
    return GateResult(passed=all(c.passed for c in checks), checks=checks)


def parse_thresholds(specs: list[str]) -> dict[str, float]:
    """``["value=2", "step_time_ms=10"]`` → per-metric threshold percents."""
    out: dict[str, float] = {}
    for spec in specs:
        metric, _, pct = spec.partition("=")
        if not metric or not pct:
            raise ValueError(f"bad --threshold {spec!r} (want metric=percent)")
        out[metric.strip()] = float(pct)
    return out
