"""The ``tony history-server`` daemon: sweep thread + HTTP query API.

Long-lived analog of the reference's dedicated history server (PAPER.md §0):
watches one or more staging roots, ingests finalized jobs into the SQLite
store on a fixed cadence (torn-file tolerant, idempotent), applies retention
and the optional staging-dir GC, and serves a JSON query API:

- ``GET /healthz``                    — liveness + store size + last sweep
- ``GET /metrics``                    — its own Prometheus exposition
- ``GET /api/jobs``                   — ingested job rows, newest first
- ``GET /api/job/<app_id>``           — one row + summary + series names
- ``GET /api/series/<app_id>/<m>``    — one distilled series
- ``GET /api/trend/<metric>``         — cross-job trend points
- ``GET /api/cluster/<metric>[/<q>]`` — pool per-queue telemetry windows
  (``cluster_series``, swept from ``tony.pool.recorder.series-file``)
- ``GET /``                           — minimal HTML index (the portal's
  ``/history`` pages are the real dashboards)

Stdlib http.server, same rationale as the portal: an ops surface, not a
control-plane dependency.
"""

from __future__ import annotations

import argparse
import json
import os
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from urllib.parse import urlparse

from tony_tpu import constants
from tony_tpu.histserver import ingest as _ingest
from tony_tpu.histserver.store import HistoryStore
from tony_tpu.obs import logging as obs_logging
from tony_tpu.obs import metrics as obs_metrics

_INGESTS = obs_metrics.counter(
    "tony_history_ingests_total",
    "sweep ingestion outcomes (ingested/unchanged/skipped/expired/errors/purged)",
    labelnames=("outcome",))
_SWEEP_SECONDS = obs_metrics.histogram(
    "tony_history_sweep_seconds", "wall time of one ingestion sweep")
_JOBS_GAUGE = obs_metrics.gauge(
    "tony_history_jobs", "jobs currently in the history store")
_GC_REMOVED = obs_metrics.counter(
    "tony_history_gc_removed_total", "staging dirs removed by the GC sweep")
_ALERT_EVALS = obs_metrics.counter(
    "tony_history_alert_evals_total",
    "finalized-job alert-rule evaluations by outcome (fired: the job ended "
    "in breach of a configured rule; ok: rules held; none: no rules "
    "configured; error: evaluation failed)",
    labelnames=("outcome",))


def default_store_path(staging_root: str) -> str:
    """Where the store lives when ``tony.history.store`` is unset: next to
    the finished history tree."""
    return os.path.join(staging_root, "history", "history.sqlite")


class HistoryServer:
    """Background sweep + HTTP API over one :class:`HistoryStore`."""

    def __init__(
        self,
        staging_roots: list[str],
        store_path: str | None = None,
        port: int = 0,
        scan_interval_s: float = 2.0,
        retention_days: float = 0.0,
        max_series_points: int = 512,
        gc_enabled: bool = False,
        cluster_series_paths: list[str] | None = None,
    ):
        if not staging_roots:
            raise ValueError("history server needs at least one staging root")
        self.staging_roots = [r.rstrip("/") for r in staging_roots]
        # pool telemetry windows (tony.history.cluster-series): JSONL files
        # the scheduler flight recorder flushes; swept into cluster_series
        self.cluster_series_paths = [p for p in (cluster_series_paths or []) if p]
        self.store = HistoryStore(
            store_path or default_store_path(self.staging_roots[0]),
            max_series_points=max_series_points)
        self.scan_interval_s = scan_interval_s
        self.retention_days = retention_days
        self.gc_enabled = gc_enabled
        self._stop = threading.Event()
        self._last_sweep_ms = 0
        self._sweeps = 0

        outer = self

        class Handler(BaseHTTPRequestHandler):
            def log_message(self, *args) -> None:  # quiet
                pass

            def do_GET(self) -> None:  # noqa: N802 (stdlib API)
                outer._handle(self)

        class Server(ThreadingHTTPServer):
            daemon_threads = True

        self._http = Server(("0.0.0.0", port), Handler)
        self._sweeper = threading.Thread(
            target=self._sweep_loop, name="history-sweep", daemon=True)
        self._serve_thread = threading.Thread(
            target=self._http.serve_forever, name="history-http", daemon=True)

    # ------------------------------------------------------------ lifecycle
    @property
    def address(self) -> tuple[str, int]:
        return self._http.server_address[:2]

    def start(self) -> None:
        self.sweep_once()  # a query right after start sees existing jobs
        self._sweeper.start()
        self._serve_thread.start()

    def stop(self) -> None:
        self._stop.set()
        self._http.shutdown()
        self._http.server_close()
        if self._sweeper.is_alive():
            self._sweeper.join(timeout=10)
        self.store.close()

    def sweep_once(self) -> dict[str, int]:
        t0 = time.perf_counter()
        counts = _ingest.sweep(
            self.store, self.staging_roots, retention_days=self.retention_days,
            on_ingested=self._evaluate_final_alerts)
        if self.cluster_series_paths:
            ccounts = _ingest.sweep_cluster_series(
                self.store, self.cluster_series_paths,
                retention_days=self.retention_days)
            counts["cluster_windows"] = ccounts["windows"]
            counts["cluster_errors"] = ccounts["errors"]
        scounts = _ingest.sweep_slo_series(
            self.store, self.staging_roots,
            retention_days=self.retention_days)
        if scounts["rows"]:
            counts["slo_rows"] = scounts["rows"]
        if scounts["errors"]:
            counts["slo_errors"] = scounts["errors"]
        if self.gc_enabled and self.retention_days > 0:
            for root in self.staging_roots:
                removed = _ingest.gc_staging(self.store, root, self.retention_days)
                if removed:
                    _GC_REMOVED.inc(len(removed))
        for outcome, n in counts.items():
            if n:
                _INGESTS.inc(n, outcome=outcome)
        _SWEEP_SECONDS.observe(time.perf_counter() - t0)
        _JOBS_GAUGE.set(self.store.count())
        self._last_sweep_ms = int(time.time() * 1000)
        self._sweeps += 1
        return counts

    def _evaluate_final_alerts(self, app_id: str, art) -> None:
        """Finalized-job alert pass: re-evaluate the job's own
        ``tony.alerts.goodput-floor`` against its FINAL ledger — the
        cross-job safety net behind the AM's live evaluation (a job whose AM
        died before resolving, or that ran with goodput disabled, is still
        caught here). Counted in ``tony_history_alert_evals_total``."""
        try:
            from tony_tpu.config import TonyConfig, keys

            row = self.store.get_job(app_id) or {}
            cfg = TonyConfig(dict(row.get("config") or {}))
            floor_raw = cfg.get(keys.ALERTS_GOODPUT_FLOOR)
            if floor_raw in (None, ""):
                _ALERT_EVALS.inc(outcome="none")
                return
            fired = float(row.get("goodput_fraction") or 0.0) < float(floor_raw)
            _ALERT_EVALS.inc(outcome="fired" if fired else "ok")
            if fired:
                obs_logging.warning(
                    f"[tony-history] {app_id} finished below its goodput "
                    f"floor: {row.get('goodput_fraction')} < {floor_raw}")
        except Exception as e:  # noqa: BLE001 — a bad config snapshot is that job's problem
            _ALERT_EVALS.inc(outcome="error")
            obs_logging.warning(
                f"[tony-history] alert evaluation for {app_id} failed: {e}")

    def _sweep_loop(self) -> None:
        while not self._stop.wait(self.scan_interval_s):
            try:
                self.sweep_once()
            except Exception as e:  # noqa: BLE001 — the daemon must outlive one bad sweep
                obs_logging.warning(f"[tony-history] sweep failed: {type(e).__name__}: {e}")

    # ------------------------------------------------------------- handlers
    def _handle(self, req: BaseHTTPRequestHandler) -> None:
        path = urlparse(req.path).path.rstrip("/")
        try:
            if path == "/healthz":
                self._json(req, {
                    "ok": True,
                    "jobs": self.store.count(),
                    "sweeps": self._sweeps,
                    "last_sweep_ms": self._last_sweep_ms,
                    "staging_roots": self.staging_roots,
                })
            elif path == "/metrics":
                body = obs_metrics.REGISTRY.render().encode()
                self._raw(req, body, "text/plain; version=0.0.4; charset=utf-8")
            elif path == "/api/jobs":
                self._json(req, self.store.list_jobs())
            elif path.startswith("/api/job/"):
                app_id = path.split("/")[3]
                job = self.store.get_job(app_id)
                if job is None:
                    self._json(req, {"error": f"{app_id} not ingested"}, status=404)
                else:
                    job["series"] = self.store.series_names(app_id)
                    self._json(req, job)
            elif path.startswith("/api/series/"):
                parts = path.split("/")
                app_id, metric = parts[3], parts[4]
                self._json(req, self.store.series(app_id, metric))
            elif path.startswith("/api/trend/"):
                self._json(req, self.store.trend(path.split("/")[3]))
            elif path.startswith("/api/cluster/"):
                # /api/cluster/<metric>[/<queue>] — pool telemetry windows
                parts = path.split("/")
                self._json(req, self.store.cluster_series(
                    parts[3], queue=parts[4] if len(parts) > 4 else None))
            elif path == "":
                self._raw(req, self._index_page(), "text/html")
            else:
                self._json(req, {"error": "not found"}, status=404)
        except (BrokenPipeError, ConnectionResetError):
            pass
        except Exception as e:  # noqa: BLE001 — one bad request must not kill the daemon
            try:
                self._json(req, {"error": f"{type(e).__name__}: {e}"}, status=500)
            except OSError:
                pass

    @staticmethod
    def _raw(req: BaseHTTPRequestHandler, body: bytes, ctype: str, status: int = 200) -> None:
        req.send_response(status)
        req.send_header("Content-Type", ctype)
        req.send_header("Content-Length", str(len(body)))
        req.end_headers()
        req.wfile.write(body)

    @classmethod
    def _json(cls, req: BaseHTTPRequestHandler, obj, status: int = 200) -> None:
        cls._raw(req, json.dumps(obj).encode(), "application/json", status=status)

    def _index_page(self) -> bytes:
        import html as _html

        rows = "".join(
            f"<tr><td><a href=\"/api/job/{_html.escape(j['app_id'])}\">"
            f"{_html.escape(j['app_id'])}</a></td>"
            f"<td>{_html.escape(j['status'])}{' (incomplete)' if j['incomplete'] else ''}</td>"
            f"<td>{j['duration_ms'] / 1000.0:.1f}s</td>"
            f"<td>{j.get('goodput_fraction', 0) or 0:.1%}</td>"
            f"<td>{j['gang_epochs']}</td>"
            f"<td>{j['resizes']}</td><td>{j['takeovers']}</td></tr>"
            for j in self.store.list_jobs(limit=200))
        return (
            "<!doctype html><html><head><title>tony history server</title></head>"
            "<body><h1>tony history server</h1>"
            f"<p>{self.store.count()} ingested job(s) · "
            '<a href="/api/jobs">jobs json</a> · <a href="/healthz">healthz</a> · '
            '<a href="/metrics">metrics</a></p>'
            "<table border=1><tr><th>application</th><th>status</th><th>duration</th>"
            "<th>goodput</th><th>epochs</th><th>resizes</th><th>takeovers</th></tr>"
            + rows + "</table></body></html>").encode()


def main(argv: list[str] | None = None) -> int:
    p = argparse.ArgumentParser(
        prog="tony history-server",
        description="persistent history daemon: ingest finalized jobs into a "
                    "queryable store (docs/history.md)")
    p.add_argument("--staging", action="append", default=[],
                   help="staging root to watch (repeatable; default $TONY_ROOT)")
    p.add_argument("--store", default=None,
                   help="SQLite store path (tony.history.store; default "
                        "<staging>/history/history.sqlite)")
    p.add_argument("--port", type=int, default=None,
                   help="HTTP port (tony.history.server.port)")
    p.add_argument("--scan-interval-ms", type=int, default=None,
                   help="sweep cadence (tony.history.scan-interval-ms)")
    p.add_argument("--retention-days", type=float, default=None,
                   help="drop store rows older than this (tony.history.retention-days; "
                        "0 keeps forever)")
    p.add_argument("--gc", action="store_true",
                   help="also remove ingested jobs' raw staging dirs past "
                        "retention (tony.history.gc.enabled)")
    p.add_argument("--cluster-series", action="append", default=[],
                   help="pool cluster-series JSONL to sweep into the "
                        "cluster_series table (repeatable; "
                        "tony.history.cluster-series)")
    args = p.parse_args(argv)

    # flags override tony-site.json which overrides defaults — the same
    # layering the pool daemon applies
    from tony_tpu.config import TonyConfig, keys

    site = os.path.join(os.getcwd(), constants.TONY_SITE_CONF)
    cfg = TonyConfig.from_layers(site_file=site if os.path.exists(site) else None)
    roots = args.staging or [constants.default_tony_root()]
    port = args.port if args.port is not None else cfg.get_int(keys.HISTORY_SERVER_PORT, 28081)
    scan_ms = (args.scan_interval_ms if args.scan_interval_ms is not None
               else cfg.get_time_ms(keys.HISTORY_SCAN_INTERVAL_MS, 2000))
    retention = (args.retention_days if args.retention_days is not None
                 else float(cfg.get(keys.HISTORY_RETENTION_DAYS) or 0))
    server = HistoryServer(
        staging_roots=roots,
        store_path=args.store or cfg.get(keys.HISTORY_STORE) or None,
        port=port,
        scan_interval_s=scan_ms / 1000.0,
        retention_days=retention,
        max_series_points=cfg.get_int(keys.HISTORY_MAX_SERIES_POINTS, 512),
        gc_enabled=args.gc or cfg.get_bool(keys.HISTORY_GC_ENABLED, False),
        cluster_series_paths=args.cluster_series or [
            p.strip()
            for p in (cfg.get(keys.HISTORY_CLUSTER_SERIES) or "").split(",")
            if p.strip()
        ],
    )
    server.start()
    host, bound = server.address
    obs_logging.info(
        f"[tony-history] serving {', '.join(roots)} on http://{host}:{bound} "
        f"(store {server.store.path})")
    try:
        while True:
            time.sleep(3600)
    except KeyboardInterrupt:
        pass
    finally:
        server.stop()
    return 0


if __name__ == "__main__":
    import sys

    sys.exit(main())
