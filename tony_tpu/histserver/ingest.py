"""Distill a finalized job's artifacts into the history store.

The pipeline the daemon (server.py), ``tony history ingest``, and ``tony
history gc`` share. Everything resolves through the artifact index
(obs/artifacts.py) — ingestion has no discovery walk of its own:

- :func:`distill` reads the ``.jhist`` event stream with torn-file
  tolerance (a job killed mid-write ingests its intact prefix and is marked
  ``incomplete``), distills per-job series from ``METRICS_SNAPSHOT`` events
  (plus a derived ``step_time_ms`` from step/timestamp deltas), counts gang
  epochs / resizes / takeovers, pairs ``QUEUE_WAIT`` episodes into a queue
  wait total, and — when the job was traced — folds checkpoint/first-step
  span totals into the summary.
- :func:`ingest_job` writes one job idempotently (re-ingest converges).
- :func:`sweep` scans staging roots for finalized-but-not-yet-ingested jobs
  (mtime change ⇒ re-ingest) and applies retention.
- :func:`gc_staging` removes raw staging dirs for jobs already ingested and
  older than the retention window — never live or un-ingested jobs.
"""

from __future__ import annotations

import math
import os
import shutil
import time
from typing import Any

from tony_tpu.obs import artifacts as obs_artifacts
from tony_tpu.obs import goodput as obs_goodput
from tony_tpu.obs import logging as obs_logging
from tony_tpu.cluster.recorder import read_window_lines
from tony_tpu.histserver.store import HistoryStore

#: train/serve metric keys distilled into per-job series (train loop's step
#: report and the serve engine's metrics pump both ride METRICS_SNAPSHOT)
SERIES_KEYS = (
    "loss", "tokens_per_sec", "mfu", "grad_norm",
    "tokens_per_s", "queue_depth", "slots_active", "ttft_s",
)

#: summary percentiles computed per series
_PERCENTILES = (("p50", 0.50), ("p90", 0.90), ("p99", 0.99))


def _percentile(sorted_vals: list[float], q: float) -> float:
    if not sorted_vals:
        return math.nan
    idx = min(int(q * len(sorted_vals)), len(sorted_vals) - 1)
    return sorted_vals[idx]


def summarize_series(points: list[tuple[int, float]]) -> dict[str, float]:
    """Percentile summary of one series: the trend charts' per-job scalar."""
    vals = sorted(v for _, v in points)
    out = {name: _percentile(vals, q) for name, q in _PERCENTILES}
    out["min"] = vals[0]
    out["max"] = vals[-1]
    out["last"] = points[-1][1]
    out["count"] = float(len(vals))
    return out


def distill(art: obs_artifacts.JobArtifacts) -> tuple[dict[str, Any], dict, dict]:
    """``(job_row, series, summary)`` from one job's artifacts.

    Raises ``ValueError`` only when there is nothing to ingest at all (no
    ``.jhist`` and no parsed history filename) — every degraded state short
    of that ingests as ``incomplete``.
    """
    events, complete = art.read_events()
    hist = art.history_file
    if hist is None and not events:
        raise ValueError(f"{art.app_id}: no history artifacts to ingest")

    series: dict[str, list[tuple[int, float]]] = {}
    gang_epochs = resizes = takeovers = 0
    queue_wait_s = 0.0
    wait_started_ms: int | None = None
    status = reason = None
    tasks = 0
    last_steps: dict[str, tuple[int, float]] = {}  # task -> (step, ts_ms)

    for ev in events:
        t = ev.type.value
        if t == "METRICS_SNAPSHOT":
            per_key: dict[str, list[float]] = {}
            step_times: list[float] = []
            for entry in ev.payload.get("tasks", []):
                train = (entry.get("metrics") or {}).get("train") or {}
                for k in SERIES_KEYS:
                    v = train.get(k)
                    if isinstance(v, (int, float)) and math.isfinite(v):
                        per_key.setdefault(k, []).append(float(v))
                # derived step time: wall delta / step delta between this
                # task's consecutive snapshots (the registry's step-time
                # histogram is deliberately stripped from the .jhist)
                step = train.get("step")
                if isinstance(step, int):
                    prev = last_steps.get(entry.get("task", "?"))
                    if prev is not None and step > prev[0] and ev.timestamp_ms > prev[1]:
                        step_times.append(
                            (ev.timestamp_ms - prev[1]) / (step - prev[0]))
                    last_steps[entry.get("task", "?")] = (step, ev.timestamp_ms)
            if step_times:
                per_key["step_time_ms"] = step_times
            for k, vals in per_key.items():
                series.setdefault(k, []).append(
                    (ev.timestamp_ms, sum(vals) / len(vals)))
        elif t == "GANG_COMPLETE":
            gang_epochs += 1
        elif t == "GANG_RESIZED":
            resizes += 1
        elif t in ("AM_TAKEOVER", "AM_TAKEOVER_DEGRADED"):
            takeovers += 1
        elif t == "QUEUE_WAIT":
            if ev.payload.get("state") == "waiting":
                wait_started_ms = ev.timestamp_ms
            elif ev.payload.get("state") == "admitted" and wait_started_ms is not None:
                waited = max(ev.timestamp_ms - wait_started_ms, 0) / 1000.0
                queue_wait_s += waited
                series.setdefault("queue_wait_s", []).append(
                    (ev.timestamp_ms, waited))
                wait_started_ms = None
        elif t == "APPLICATION_FINISHED":
            status = ev.payload.get("status")
            reason = ev.payload.get("reason")
            tasks = len(ev.payload.get("tasks") or [])

    summary: dict[str, Any] = {
        k: summarize_series(pts) for k, pts in series.items() if pts
    }
    if reason:
        summary["reason"] = str(reason)

    # traced jobs: fold checkpoint / compile / queue span totals in (the
    # shared span reader tolerates torn span files the same way)
    spans = obs_artifacts.load_spans(art.trace_dir)
    # goodput accounting: the exact phase partition (obs/goodput.py) becomes
    # two job columns (trend-able across runs) + the full phase breakdown
    # and alert/straggler history in the summary
    try:
        ledger = obs_goodput.build_ledger(
            art.app_id, events, spans,
            now_ms=events[-1].timestamp_ms if events else 0)
        summary["goodput"] = {
            "fraction": round(ledger.goodput_fraction, 6),
            "phases_ms": dict(ledger.phases_ms),
        }
        skew = ledger.skew_by_task()
        if skew:
            summary["goodput"]["skew_by_task"] = {
                t: round(r, 4) for t, r in skew.items()}
        goodput_s = round(ledger.phases_ms.get("productive", 0) / 1000.0, 3)
        badput_s = round(sum(ledger.badput_ms().values()) / 1000.0, 3)
        goodput_fraction = round(ledger.goodput_fraction, 6)
    except Exception as e:  # noqa: BLE001 — a degenerate stream still ingests
        obs_logging.warning(
            f"[tony-history] goodput ledger for {art.app_id} failed: {e}")
        goodput_s, badput_s, goodput_fraction = 0.0, 0.0, 0.0
    alert_hist = [
        {"state": ("fired" if ev.type.value == "ALERT_FIRED" else "resolved"),
         "ts_ms": ev.timestamp_ms,
         "rule": ev.payload.get("rule"), "value": ev.payload.get("value")}
        for ev in events
        if ev.type.value in ("ALERT_FIRED", "ALERT_RESOLVED")
    ]
    if alert_hist:
        summary["alerts"] = alert_hist
    stragglers = sorted({
        str(ev.payload.get("task")) for ev in events
        if ev.type.value == "STRAGGLER_DETECTED"
    })
    if stragglers:
        summary["stragglers"] = stragglers
    if spans:
        def total(names: tuple[str, ...]) -> float:
            return sum(
                max(s.get("end_ms", s["start_ms"]) - s["start_ms"], 0.0) / 1000.0
                for s in spans if s.get("name") in names)

        ckpt_s = total(("ckpt.save", "ckpt.restore"))
        if ckpt_s:
            summary["ckpt_s"] = {"total": ckpt_s}
        firsts = [
            max(s.get("end_ms", s["start_ms"]) - s["start_ms"], 0.0) / 1000.0
            for s in spans if s.get("name") == "train.first_step"]
        if firsts:
            summary["first_step_s"] = {"max": max(firsts)}

    started_ms = hist.started_ms if hist else (events[0].timestamp_ms if events else 0)
    completed_ms = hist.completed_ms if hist else (events[-1].timestamp_ms if events else 0)
    job = {
        "app_id": art.app_id,
        # the encoded filename is the finalization authority; the event
        # stream's APPLICATION_FINISHED may be missing from a torn file
        "status": (hist.status if hist else None) or status or "UNKNOWN",
        "user": hist.user if hist else "",
        "started_ms": started_ms,
        "completed_ms": completed_ms,
        "duration_ms": max(completed_ms - started_ms, 0),
        "incomplete": not complete,
        "tasks": tasks,
        "gang_epochs": gang_epochs,
        "resizes": resizes,
        "takeovers": takeovers,
        "queue_wait_s": round(queue_wait_s, 3),
        "goodput_s": goodput_s,
        "badput_s": badput_s,
        "goodput_fraction": goodput_fraction,
        "staging_dir": art.staging_dir,
        "source_path": art.jhist_path or "",
        "source_mtime_ns": _mtime_ns(art.jhist_path),
    }
    return job, series, summary


def _mtime_ns(path: str | None) -> int:
    if not path:
        return 0
    try:
        return os.stat(path).st_mtime_ns
    except OSError:
        return 0


def _config_snapshot(art: obs_artifacts.JobArtifacts) -> dict[str, Any]:
    if not art.config_snapshot_path:
        return {}
    try:
        import json

        with open(art.config_snapshot_path) as f:
            cfg = json.load(f)
        return cfg if isinstance(cfg, dict) else {}
    except (OSError, ValueError):
        return {}


def ingest_job(store: HistoryStore, art: obs_artifacts.JobArtifacts) -> str:
    """Ingest one finalized job; returns the outcome (``ingested`` /
    ``unchanged`` / ``skipped``). Torn or truncated artifacts ingest as
    ``incomplete`` rather than raising (satellite contract)."""
    if not art.finalized:
        return "skipped"  # live or never-started: not ours to touch
    known = store.source_mtime_ns(art.app_id)
    if known is not None and known == _mtime_ns(art.jhist_path):
        return "unchanged"
    job, series, summary = distill(art)
    store.put_job(job, series=series, summary=summary, config=_config_snapshot(art))
    return "ingested"


def sweep(
    store: HistoryStore,
    staging_roots: list[str],
    retention_days: float = 0.0,
    now_ms: int | None = None,
    on_ingested=None,
) -> dict[str, int]:
    """One ingestion pass over every staging root: ingest finalized jobs
    (new or changed), then apply retention. Returns outcome counts.
    ``on_ingested(app_id, artifacts)`` fires for each newly-(re)ingested job
    — the daemon hangs its finalized-job alert evaluation there; a hook
    failure counts as that job's error, never stalls the sweep."""
    counts = {"ingested": 0, "unchanged": 0, "skipped": 0, "expired": 0,
              "errors": 0, "purged": 0}
    now = now_ms if now_ms is not None else int(time.time() * 1000)
    cutoff = now - int(retention_days * 86_400_000) if retention_days > 0 else None
    # O(changed) fast path (docs/performance.md "Control-plane scalability"):
    # one query for every ingested job's source mtime, so the steady-state
    # re-sweep — thousands of already-ingested jobs, nothing new — costs a
    # stat per job instead of a store query + full artifact-index resolution
    known_mtimes = store.source_mtimes()
    for root in staging_roots:
        # one walk of the finished tree per root (not per job): jobs whose
        # staging dir was GC'd still exist only here, so the map is both the
        # lookup shortcut and the fresh-store rebuild source
        finished = obs_artifacts.finished_index(os.path.join(root, "history"))
        ids = obs_artifacts.staged_ids(root)
        ids += [a for a in sorted(finished) if a not in ids]
        for app_id in ids:
            hint = finished.get(app_id)
            # never ingest work retention would purge right back out — the
            # finished .jhist outlives the store row by design, and the
            # ingest→purge cycle would otherwise repeat every sweep forever
            if cutoff is not None and hint is not None and hint[1].completed_ms < cutoff:
                counts["expired"] += 1
                continue
            if (
                hint is not None
                and known_mtimes.get(app_id) == _mtime_ns(hint[0])
            ):
                counts["unchanged"] += 1
                continue
            try:
                art = obs_artifacts.index(root, app_id, finished=hint)
                outcome = ingest_job(store, art)
                if outcome == "ingested" and on_ingested is not None:
                    on_ingested(app_id, art)
                # counted only after the hook: a raising hook is THIS job's
                # error, not an extra outcome on top of "ingested"
                counts[outcome] += 1
            except Exception as e:  # noqa: BLE001 — one bad job must not stall the sweep
                counts["errors"] += 1
                obs_logging.warning(
                    f"[tony-history] ingest of {app_id} failed: {type(e).__name__}: {e}")
    if cutoff is not None:
        counts["purged"] = len(store.purge_older_than(cutoff))
    return counts


def sweep_cluster_series(
    store: HistoryStore,
    paths: list[str],
    retention_days: float = 0.0,
    now_ms: int | None = None,
) -> dict[str, int]:
    """One pass over the pool's cluster-series JSONL files (the scheduler
    flight recorder's finalized per-queue telemetry windows,
    ``tony.pool.recorder.series-file``) into the store's ``cluster_series``
    table, then retention.

    Same discipline as the job sweep: idempotent (rows REPLACE on their
    window key, so re-reading a growing file converges), torn-tail tolerant
    (a line the pool died mid-append is skipped), per-file error isolation.
    Files are small by construction — one line per queue per
    ``tony.pool.recorder.window-ms`` — so re-reading whole files each sweep
    costs less than one job ingest."""
    counts = {"files": 0, "windows": 0, "rows": 0, "errors": 0, "purged_rows": 0}
    for path in paths:
        if not path:
            continue
        try:
            windows = list(read_window_lines(path))
            source = os.path.splitext(os.path.basename(path))[0]
            by_source: dict[str, list[dict[str, Any]]] = {}
            for w in windows:
                by_source.setdefault(str(w.get("source") or source), []).append(w)
            for src, ws in by_source.items():
                counts["rows"] += store.put_cluster_windows(src, ws)
            counts["windows"] += len(windows)
            counts["files"] += 1
        except Exception as e:  # noqa: BLE001 — one bad file must not stall the sweep
            counts["errors"] += 1
            obs_logging.warning(
                f"[tony-history] cluster-series ingest of {path} failed: "
                f"{type(e).__name__}: {e}")
    if retention_days > 0:
        now = now_ms if now_ms is not None else int(time.time() * 1000)
        cutoff = now - int(retention_days * 86_400_000)
        counts["purged_rows"] = store.purge_cluster_older_than(cutoff)
    return counts


def sweep_slo_series(
    store: HistoryStore,
    staging_roots: list[str],
    retention_days: float = 0.0,
    now_ms: int | None = None,
) -> dict[str, int]:
    """One pass over every staged app's ``slo.jsonl`` (the AM's SLO engine
    appends one budget-bucket row per objective per tick, obs/slo.py
    ``append_windows``) into the store's ``slo_series`` table, then
    retention.

    Same discipline as the cluster-series sweep: idempotent (rows REPLACE on
    (source, objective, bucket) and the AM re-emits the current bucket with
    fuller counts each tick, so the last write for a bucket wins), torn-tail
    tolerant (a line the AM died mid-append is skipped), per-file error
    isolation. This is what makes ``tony slo verdict`` readable from history
    alone — no live AM required."""
    import json as _json

    counts = {"files": 0, "rows": 0, "errors": 0, "purged_rows": 0}
    for root in staging_roots:
        for app_id in obs_artifacts.staged_ids(root):
            path = os.path.join(root, app_id, "slo.jsonl")
            if not os.path.isfile(path):
                continue
            try:
                rows: list[dict[str, Any]] = []
                with open(path, encoding="utf-8", errors="replace") as f:
                    for line in f:
                        line = line.strip()
                        if not line:
                            continue
                        try:
                            doc = _json.loads(line)
                        except ValueError:
                            continue  # torn tail / partial append
                        if isinstance(doc, dict):
                            rows.append(doc)
                counts["rows"] += store.put_slo_windows(
                    str(rows[0].get("app_id") or app_id) if rows else app_id,
                    rows)
                counts["files"] += 1
            except Exception as e:  # noqa: BLE001 — one bad file must not stall the sweep
                counts["errors"] += 1
                obs_logging.warning(
                    f"[tony-history] slo-series ingest of {path} failed: "
                    f"{type(e).__name__}: {e}")
    if retention_days > 0:
        now = now_ms if now_ms is not None else int(time.time() * 1000)
        cutoff = now - int(retention_days * 86_400_000)
        counts["purged_rows"] = store.purge_slo_older_than(cutoff)
    return counts


def gc_staging(
    store: HistoryStore,
    staging_root: str,
    retention_days: float,
    dry_run: bool = False,
    now_ms: int | None = None,
) -> list[tuple[str, str]]:
    """Remove raw staging dirs for jobs that are (a) ingested, (b) finalized
    on disk, and (c) completed more than ``retention_days`` ago. Live jobs
    (no finished ``.jhist``) and un-ingested jobs are NEVER touched; the
    finished history tree itself is preserved (the store is a distillation,
    the ``.jhist`` stays the forensic record). Returns ``(app_id, path)``
    pairs removed (or would-be removed under ``dry_run``)."""
    if retention_days <= 0:
        return []
    now = now_ms if now_ms is not None else int(time.time() * 1000)
    cutoff = now - int(retention_days * 86_400_000)
    removed: list[tuple[str, str]] = []
    for app_id in obs_artifacts.staged_ids(staging_root):
        art = obs_artifacts.index(staging_root, app_id)
        if not art.finalized:
            continue  # live (or unfinalized): never GC'd
        row = store.get_job(app_id)
        if row is None:
            continue  # un-ingested: the raw artifacts are the only record
        if not row.get("completed_ms") or row["completed_ms"] >= cutoff:
            continue
        removed.append((app_id, art.staging_dir))
        if not dry_run:
            shutil.rmtree(art.staging_dir, ignore_errors=True)
    return removed
