"""Persistent history tier: the reference's dedicated history server
(``tony-history-server``, PAPER.md §0 layer map) rebuilt for this framework.

Finalized jobs' on-disk artifacts — the ``.jhist`` event stream, span JSONL,
metrics snapshots, profile captures — are write-only archaeology the moment
the AM exits; this package turns them into a queryable, retained store:

- ``store.py``   — SQLite-backed job/series store with retention + compaction
- ``ingest.py``  — artifact-index-driven distiller (torn-file tolerant) and
  the staging-root sweep / GC the daemon and ``tony history ingest|gc`` share
- ``server.py``  — the ``tony history-server`` daemon: background sweep +
  HTTP query API with its own ``/metrics`` and ``/healthz``
- ``gate.py``    — the ``tony bench --gate`` perf-regression contract over
  the checked-in ``BENCH_*.json`` trajectory

Docs: docs/history.md. Config: the ``tony.history.*`` keys in
config/keys.py.
"""

from tony_tpu.histserver.store import HistoryStore

__all__ = ["HistoryStore", "HistoryServer"]


def __getattr__(name):
    # HistoryServer is daemon-only: importing it registers the daemon's
    # metrics into the process-global registry, which a store-only consumer
    # (the portal's /history pages, the CLI) must not do — lazy by PEP 562
    if name == "HistoryServer":
        from tony_tpu.histserver.server import HistoryServer

        return HistoryServer
    raise AttributeError(name)
