"""SQLite-backed history store: one row per ingested job + distilled series.

The persistence layer under the history server (docs/history.md). Schema:

- ``jobs``: verdict, timings, gang counters (epochs / resizes / takeovers),
  queue wait, an ``incomplete`` flag (torn/truncated ``.jhist``), the
  distilled per-metric ``summary`` percentiles, and the job's frozen config
  snapshot — everything ``tony history list|show|compare`` and the portal's
  ``/history`` trend pages read.
- ``series``: per-job time series (MFU, loss, tokens/s, queue depth, …)
  distilled from ``METRICS_SNAPSHOT`` events, compacted to at most
  ``max_series_points`` evenly-strided points per (job, metric) at write
  time (``tony.history.max-series-points``).
- ``cluster_series``: CLUSTER-level per-queue telemetry windows (the pool's
  scheduler flight recorder flushes them to
  ``tony.pool.recorder.series-file``; ``ingest.sweep_cluster_series``
  distills each window's metrics into one row per metric). Keyed
  (source, queue, metric, window_start_ms) so re-ingesting the same file
  converges — same idempotence discipline as jobs. The portal's
  ``/history`` capacity dashboards chart these across runs
  (docs/scheduling.md "Explaining decisions").
- ``slo_series``: per-app SLO budget buckets (obs/slo.py appends one JSONL
  row per objective per tick to the app's ``slo.jsonl``;
  ``ingest.sweep_slo_series`` folds them in). Keyed
  (source, objective, window_start_ms) with REPLACE semantics — the AM
  re-emits the CURRENT bucket each tick with fuller counts, so the last
  write wins and re-sweeping converges. ``tony slo verdict`` aggregates
  these (good/bad sums per objective) instead of trusting any in-process
  state (docs/observability.md "SLOs & error budgets").

Writes are idempotent by construction: :meth:`HistoryStore.put_job` replaces
the job row and its series in one transaction, so re-ingesting a job (the
sweep after a restart, or ``tony history ingest`` run twice) converges
instead of duplicating. Retention (``tony.history.retention-days``) is
:meth:`purge_older_than` — the daemon applies it on its sweep cadence.

SQLite is stdlib, single-file, and crash-safe under WAL — the right weight
for a control-plane store that sees one write per finished job.

Locking: ONE connection serialized by ONE lock — the lock's whole job is to
be held across SQLite statements, and nothing is ever acquired under it (a
leaf in the lock-order graph, enforced by ``tony lint``'s lock-ordering
checker). Python-side work — row building, series compaction, JSON
encoding — happens OUTSIDE it, so the critical sections are exactly the
statements.
"""
# lint: disable-file=blocking-under-lock — the store lock IS the single-SQLite-connection serializer; it exists to be held across statements and is a leaf (nothing acquired under it)

from __future__ import annotations

import json
import os
import sqlite3
import time
from typing import Any

from tony_tpu.obs import locktrace

_SCHEMA = """
CREATE TABLE IF NOT EXISTS jobs (
  app_id TEXT PRIMARY KEY,
  status TEXT NOT NULL,
  user TEXT DEFAULT '',
  started_ms INTEGER DEFAULT 0,
  completed_ms INTEGER DEFAULT 0,
  duration_ms INTEGER DEFAULT 0,
  incomplete INTEGER DEFAULT 0,
  tasks INTEGER DEFAULT 0,
  gang_epochs INTEGER DEFAULT 0,
  resizes INTEGER DEFAULT 0,
  takeovers INTEGER DEFAULT 0,
  queue_wait_s REAL DEFAULT 0.0,
  goodput_s REAL DEFAULT 0.0,
  badput_s REAL DEFAULT 0.0,
  goodput_fraction REAL DEFAULT 0.0,
  staging_dir TEXT DEFAULT '',
  source_path TEXT DEFAULT '',
  source_mtime_ns INTEGER DEFAULT 0,
  ingested_ms INTEGER DEFAULT 0,
  summary TEXT DEFAULT '{}',
  config TEXT DEFAULT '{}'
);
CREATE TABLE IF NOT EXISTS series (
  app_id TEXT NOT NULL,
  metric TEXT NOT NULL,
  seq INTEGER NOT NULL,
  ts_ms INTEGER DEFAULT 0,
  value REAL NOT NULL,
  PRIMARY KEY (app_id, metric, seq)
);
CREATE INDEX IF NOT EXISTS series_by_metric ON series (metric, app_id);
CREATE TABLE IF NOT EXISTS cluster_series (
  source TEXT NOT NULL,
  queue TEXT NOT NULL,
  metric TEXT NOT NULL,
  window_start_ms INTEGER NOT NULL,
  window_end_ms INTEGER DEFAULT 0,
  value REAL NOT NULL,
  PRIMARY KEY (source, queue, metric, window_start_ms)
);
CREATE INDEX IF NOT EXISTS cluster_series_by_metric
  ON cluster_series (metric, source, queue);
CREATE TABLE IF NOT EXISTS slo_series (
  source TEXT NOT NULL,
  objective TEXT NOT NULL,
  window_start_ms INTEGER NOT NULL,
  window_end_ms INTEGER DEFAULT 0,
  good INTEGER DEFAULT 0,
  bad INTEGER DEFAULT 0,
  burn_fast REAL,
  burn_slow REAL,
  budget_remaining REAL,
  target REAL DEFAULT 0.0,
  unit TEXT DEFAULT '',
  PRIMARY KEY (source, objective, window_start_ms)
);
CREATE INDEX IF NOT EXISTS slo_series_by_objective
  ON slo_series (objective, source);
"""

#: jobs columns callers may pass into put_job (summary/config are JSON'd)
_JOB_FIELDS = (
    "app_id", "status", "user", "started_ms", "completed_ms", "duration_ms",
    "incomplete", "tasks", "gang_epochs", "resizes", "takeovers",
    "queue_wait_s", "goodput_s", "badput_s", "goodput_fraction",
    "staging_dir", "source_path", "source_mtime_ns",
)


def compact_series(points: list[tuple[int, float]], max_points: int) -> list[tuple[int, float]]:
    """Downsample to at most ``max_points`` by even striding, always keeping
    the first and last point (trend endpoints are what cross-job charts
    anchor on). ``max_points`` < 2 disables compaction."""
    if max_points < 2 or len(points) <= max_points:
        return points
    step = (len(points) - 1) / (max_points - 1)
    picked = [points[round(i * step)] for i in range(max_points - 1)]
    picked.append(points[-1])
    return picked


class HistoryStore:
    """Thread-safe wrapper around one SQLite database file (or ':memory:')."""

    def __init__(self, path: str, max_series_points: int = 512):
        self.path = path
        self.max_series_points = max_series_points
        parent = os.path.dirname(path)
        if parent and path != ":memory:":
            os.makedirs(parent, exist_ok=True)
        # one connection, serialized by our lock: the store sees one write
        # per finished job and low-rate reads — simplicity over pooling
        self._db = sqlite3.connect(path, check_same_thread=False)
        self._db.row_factory = sqlite3.Row
        self._lock = locktrace.make_lock("store.HistoryStore._lock")
        with self._lock:
            if path != ":memory:":
                self._db.execute("PRAGMA journal_mode=WAL")
            self._db.executescript(_SCHEMA)
            # migrate pre-goodput stores in place: CREATE IF NOT EXISTS
            # never adds columns to an existing table
            have = {r["name"] for r in self._db.execute("PRAGMA table_info(jobs)")}
            for col, decl in (("goodput_s", "REAL DEFAULT 0.0"),
                              ("badput_s", "REAL DEFAULT 0.0"),
                              ("goodput_fraction", "REAL DEFAULT 0.0")):
                if col not in have:
                    self._db.execute(f"ALTER TABLE jobs ADD COLUMN {col} {decl}")
            self._db.commit()

    def close(self) -> None:
        with self._lock:
            self._db.close()

    # ------------------------------------------------------------- writes
    def put_job(
        self,
        job: dict[str, Any],
        series: dict[str, list[tuple[int, float]]] | None = None,
        summary: dict[str, Any] | None = None,
        config: dict[str, Any] | None = None,
    ) -> None:
        """Insert or REPLACE one job and its series atomically (idempotent
        re-ingest: running this twice for the same app converges)."""
        # absent fields are omitted so the column DEFAULTs apply (an explicit
        # None would insert NULL over them)
        row = {f: job[f] for f in _JOB_FIELDS if job.get(f) is not None}
        if not row.get("app_id") or not row.get("status"):
            raise ValueError("put_job requires app_id and status")
        row["incomplete"] = int(bool(row.get("incomplete")))
        row["ingested_ms"] = int(time.time() * 1000)
        row["summary"] = json.dumps(summary or {}, sort_keys=True)
        row["config"] = json.dumps(config or {}, sort_keys=True)
        cols = ", ".join(row)
        qs = ", ".join("?" for _ in row)
        # series compaction + row building are O(points) Python work —
        # done out here so writers behind the lock only wait on SQLite
        series_rows = [
            (row["app_id"], metric, i, int(ts), float(v))
            for metric, points in (series or {}).items()
            for i, (ts, v) in enumerate(
                compact_series(points, self.max_series_points))
        ]
        with self._lock:
            try:
                self._db.execute(
                    f"INSERT OR REPLACE INTO jobs ({cols}) VALUES ({qs})",
                    tuple(row.values()))
                self._db.execute("DELETE FROM series WHERE app_id = ?", (row["app_id"],))
                self._db.executemany(
                    "INSERT OR REPLACE INTO series (app_id, metric, seq, ts_ms, value) "
                    "VALUES (?, ?, ?, ?, ?)", series_rows)
                self._db.commit()
            except Exception:
                self._db.rollback()
                raise

    def purge_older_than(self, cutoff_ms: int) -> list[str]:
        """Drop jobs (and their series) completed before ``cutoff_ms``;
        returns the purged app ids (retention enforcement)."""
        with self._lock:
            rows = self._db.execute(
                "SELECT app_id FROM jobs WHERE completed_ms > 0 AND completed_ms < ?",
                (cutoff_ms,)).fetchall()
            ids = [r["app_id"] for r in rows]
            if ids:
                qs = ",".join("?" for _ in ids)
                self._db.execute(f"DELETE FROM series WHERE app_id IN ({qs})", ids)
                self._db.execute(f"DELETE FROM jobs WHERE app_id IN ({qs})", ids)
                self._db.commit()
            return ids

    # ------------------------------------------------- cluster telemetry
    def put_cluster_windows(self, source: str, windows: list[dict[str, Any]]) -> int:
        """Fold finalized per-queue telemetry windows (recorder.py shape:
        ``{queue, window_start_ms, window_end_ms, metrics: {...}}``) into
        ``cluster_series`` rows — one row per (window, metric), REPLACE on
        the primary key so re-sweeping the same file converges. Returns the
        rows written."""
        rows = [
            (source, str(w["queue"]), str(metric),
             int(w["window_start_ms"]), int(w.get("window_end_ms") or 0),
             float(value))
            for w in windows
            for metric, value in (w.get("metrics") or {}).items()
            if isinstance(value, (int, float))
        ]
        if not rows:
            return 0
        with self._lock:
            try:
                self._db.executemany(
                    "INSERT OR REPLACE INTO cluster_series "
                    "(source, queue, metric, window_start_ms, window_end_ms, value) "
                    "VALUES (?, ?, ?, ?, ?, ?)", rows)
                self._db.commit()
            except Exception:
                self._db.rollback()
                raise
        return len(rows)

    # ----------------------------------------------------- SLO telemetry
    def put_slo_windows(self, source: str, rows: list[dict[str, Any]]) -> int:
        """Fold SLO budget-bucket rows (obs/slo.py ``window_rows`` shape)
        into ``slo_series`` — one row per (source, objective, bucket),
        REPLACE on the primary key. The AM appends a fresh row for the
        CURRENT bucket every tick, so later sweeps overwrite earlier
        partial counts with fuller ones: the last write for a bucket is the
        complete one, and re-sweeping converges. Returns rows written."""
        tuples = [
            (source, str(r["objective"]),
             int(r["window_start_ms"]), int(r.get("window_end_ms") or 0),
             int(r.get("good") or 0), int(r.get("bad") or 0),
             r.get("burn_fast"), r.get("burn_slow"),
             r.get("budget_remaining"), float(r.get("target") or 0.0),
             str(r.get("unit") or ""))
            for r in rows
            if r.get("objective") and r.get("window_start_ms") is not None
        ]
        if not tuples:
            return 0
        with self._lock:
            try:
                self._db.executemany(
                    "INSERT OR REPLACE INTO slo_series "
                    "(source, objective, window_start_ms, window_end_ms, "
                    " good, bad, burn_fast, burn_slow, budget_remaining, "
                    " target, unit) VALUES (?, ?, ?, ?, ?, ?, ?, ?, ?, ?, ?)",
                    tuples)
                self._db.commit()
            except Exception:
                self._db.rollback()
                raise
        return len(tuples)

    def slo_series(
        self, objective: str | None = None, source: str | None = None,
        since_ms: int = 0, limit: int = 0,
    ) -> list[dict[str, Any]]:
        """SLO budget-bucket rows oldest first — what ``tony slo verdict``
        and the portal's ``/slo`` history strip aggregate over."""
        q = ("SELECT source, objective, window_start_ms, window_end_ms, "
             "good, bad, burn_fast, burn_slow, budget_remaining, target, unit "
             "FROM slo_series WHERE 1=1")
        params: list[Any] = []
        if objective is not None:
            q += " AND objective = ?"
            params.append(objective)
        if source is not None:
            q += " AND source = ?"
            params.append(source)
        if since_ms:
            q += " AND window_start_ms > ?"
            params.append(since_ms)
        q += " ORDER BY window_start_ms"
        with self._lock:
            rows = self._db.execute(q, params).fetchall()
        out = [dict(r) for r in rows]
        return out[-limit:] if limit else out

    def purge_slo_older_than(self, cutoff_ms: int) -> int:
        """Retention for SLO buckets (same sweep discipline as cluster
        telemetry): buckets that ENDED before ``cutoff_ms`` are dropped."""
        with self._lock:
            cur = self._db.execute(
                "DELETE FROM slo_series WHERE window_end_ms > 0 "
                "AND window_end_ms < ?", (cutoff_ms,))
            self._db.commit()
            return cur.rowcount

    def cluster_series(
        self, metric: str, queue: str | None = None, source: str | None = None,
        limit: int = 0,
    ) -> list[dict[str, Any]]:
        """Window points for one cluster metric, oldest first — the capacity
        dashboards' chart source."""
        q = ("SELECT source, queue, window_start_ms, window_end_ms, value "
             "FROM cluster_series WHERE metric = ?")
        params: list[Any] = [metric]
        if queue is not None:
            q += " AND queue = ?"
            params.append(queue)
        if source is not None:
            q += " AND source = ?"
            params.append(source)
        q += " ORDER BY window_start_ms"
        with self._lock:
            rows = self._db.execute(q, params).fetchall()
        out = [dict(r) for r in rows]
        return out[-limit:] if limit else out

    def cluster_trace(self, source: str | None = None) -> list[dict[str, Any]]:
        """Export cluster telemetry as reconstructed WINDOWS (the
        recorder.py shape ``{queue, window_start_ms, window_end_ms,
        metrics: {...}}``, oldest first) — the trace-replay feed:
        ``tony sim --from-history`` and the portal what-if page rebuild a
        synthetic workload from exactly this (cluster/replay.py,
        docs/scheduling.md "What-if capacity planning")."""
        q = ("SELECT source, queue, metric, window_start_ms, window_end_ms, "
             "value FROM cluster_series")
        params: list[Any] = []
        if source is not None:
            q += " WHERE source = ?"
            params.append(source)
        q += " ORDER BY window_start_ms, queue"
        with self._lock:
            rows = self._db.execute(q, params).fetchall()
        windows: dict[tuple[str, str, int], dict[str, Any]] = {}
        for r in rows:
            key = (r["source"], r["queue"], int(r["window_start_ms"]))
            w = windows.setdefault(key, {
                "source": r["source"], "queue": r["queue"],
                "window_start_ms": int(r["window_start_ms"]),
                "window_end_ms": int(r["window_end_ms"] or 0),
                "metrics": {},
            })
            w["metrics"][str(r["metric"])] = float(r["value"])
        return list(windows.values())

    def cluster_queues(self) -> list[tuple[str, str]]:
        """Distinct (source, queue) pairs with any telemetry windows."""
        with self._lock:
            rows = self._db.execute(
                "SELECT DISTINCT source, queue FROM cluster_series "
                "ORDER BY source, queue").fetchall()
        return [(r["source"], r["queue"]) for r in rows]

    def purge_cluster_older_than(self, cutoff_ms: int) -> int:
        """Retention for cluster telemetry (same sweep discipline as jobs):
        windows that ENDED before ``cutoff_ms`` are dropped."""
        with self._lock:
            cur = self._db.execute(
                "DELETE FROM cluster_series WHERE window_end_ms > 0 "
                "AND window_end_ms < ?", (cutoff_ms,))
            self._db.commit()
            return cur.rowcount

    # -------------------------------------------------------------- reads
    @staticmethod
    def _job_dict(row: sqlite3.Row) -> dict[str, Any]:
        d = dict(row)
        for k in ("summary", "config"):
            try:
                d[k] = json.loads(d.get(k) or "{}")
            except ValueError:
                d[k] = {}
        d["incomplete"] = bool(d.get("incomplete"))
        return d

    def get_job(self, app_id: str) -> dict[str, Any] | None:
        with self._lock:
            row = self._db.execute(
                "SELECT * FROM jobs WHERE app_id = ?", (app_id,)).fetchone()
        return self._job_dict(row) if row else None

    def list_jobs(self, limit: int = 0) -> list[dict[str, Any]]:
        """All jobs, newest completion first."""
        q = "SELECT * FROM jobs ORDER BY completed_ms DESC, app_id DESC"
        if limit:
            q += f" LIMIT {int(limit)}"
        with self._lock:
            rows = self._db.execute(q).fetchall()
        return [self._job_dict(r) for r in rows]

    def count(self) -> int:
        with self._lock:
            return int(self._db.execute("SELECT COUNT(*) FROM jobs").fetchone()[0])

    def source_mtime_ns(self, app_id: str) -> int | None:
        """The ingested source file's mtime, for sweep change detection."""
        with self._lock:
            row = self._db.execute(
                "SELECT source_mtime_ns FROM jobs WHERE app_id = ?", (app_id,)).fetchone()
        return int(row[0]) if row else None

    def source_mtimes(self) -> dict[str, int]:
        """Every ingested job's source mtime in ONE query — the sweep's
        unchanged-job fast path (docs/performance.md "Control-plane
        scalability"): re-sweeping a 10k-job store must not pay one lookup
        query plus one artifact-index resolution per already-ingested job."""
        with self._lock:
            rows = self._db.execute(
                "SELECT app_id, source_mtime_ns FROM jobs").fetchall()
        return {str(r["app_id"]): int(r["source_mtime_ns"]) for r in rows}

    def series(self, app_id: str, metric: str) -> list[tuple[int, float]]:
        with self._lock:
            rows = self._db.execute(
                "SELECT ts_ms, value FROM series WHERE app_id = ? AND metric = ? "
                "ORDER BY seq", (app_id, metric)).fetchall()
        return [(int(r["ts_ms"]), float(r["value"])) for r in rows]

    def series_names(self, app_id: str) -> list[str]:
        with self._lock:
            rows = self._db.execute(
                "SELECT DISTINCT metric FROM series WHERE app_id = ? ORDER BY metric",
                (app_id,)).fetchall()
        return [r["metric"] for r in rows]

    def trend(self, metric: str, stat: str = "p50") -> list[dict[str, Any]]:
        """Cross-job trend: one ``{app_id, completed_ms, value}`` point per
        job that distilled ``metric``, oldest completion first — the
        portal's runs-over-time charts. ``stat`` picks the summary
        percentile (``p50``/``p90``/``last``/``max``…); job-level counters
        (``gang_epochs``/``resizes``/``takeovers``/``queue_wait_s``/
        ``duration_ms``) come straight off the row."""
        out: list[dict[str, Any]] = []
        for job in sorted(self.list_jobs(), key=lambda j: (j["completed_ms"], j["app_id"])):
            if metric in ("gang_epochs", "resizes", "takeovers",
                          "queue_wait_s", "duration_ms",
                          "goodput_s", "badput_s", "goodput_fraction"):
                value: Any = job.get(metric)
            else:
                value = (job.get("summary", {}).get(metric) or {}).get(stat)
            if value is None:
                continue
            out.append({"app_id": job["app_id"],
                        "completed_ms": job["completed_ms"],
                        "value": float(value)})
        return out
