"""Pallas remote-DMA ring attention: the hand-overlapped CP data plane.

The XLA implementation (parallel/context.ring_attention) expresses the ring
as ``lax.scan`` + ``ppermute`` and leaves transfer/compute overlap to the
compiler. This module is the same blockwise-softmax schedule written as ONE
Pallas kernel per device: KV shards travel the ``context``-axis ring as
inter-chip RDMA (``make_async_remote_copy`` over ICI) between **HBM-resident
double-buffered slots**, while the kernel overlaps each transfer with the
flash-attention math on the slot it already holds — the TPU analog of the
reference's NCCL-ring data plane, which lived inside user frameworks
(SURVEY.md §2.6), built per the Pallas guide's ring-collective pattern.

VMEM discipline: only tiles pass through VMEM (q/k/v blocks and the f32
softmax state for one q block), so per-device shard size is bounded by HBM,
not VMEM, and KV stays at Hkv width end to end (GQA-native — q heads alias
onto kv heads inside the compute loop, never broadcast).

Differentiable: the custom VJP recomputes the backward through the XLA ring
(numerically identical schedule), so the kernel drops into training models
wherever ``ring_attention`` is used (``LlamaConfig(cp_impl="pallas")``).

Validated in TPU-interpret mode (which emulates RDMA + semaphores across
shard_map devices, with race detection) on a virtual CPU mesh; the real-ICI
path uses the same code with ``interpret=None`` on a physical slice.
"""

from __future__ import annotations

import functools
import os
from typing import Any

import jax
import jax.numpy as jnp

from tony_tpu.ops.attention import NEG_INF, _STAT_LANES

# Registry of Pallas collective_ids in this program. A collective_id names the
# cross-device barrier-semaphore set; two concurrently-live collective kernels
# sharing an id would alias barrier counts and silently hang. Reserve ids here.
RING_ATTENTION_COLLECTIVE_ID = 7
# next free id: 8


def default_interpret():
    """InterpretParams when the env asks for emulated kernels, else False
    (same TONY_PALLAS_INTERPRET contract as ops/attention.py)."""
    if os.environ.get("TONY_PALLAS_INTERPRET", "") == "1":
        from jax.experimental.pallas import tpu as pltpu

        return pltpu.InterpretParams()
    return False


def _ring_fwd_kernel(
    my_ref, q_hbm, k_hbm, v_hbm, o_hbm,
    kbuf, vbuf, acc_hbm, m_hbm, l_hbm,
    qt, kt, vt, acct, mt, lt, ot, csem, send_sem, recv_sem, ready_sem,
    *, n: int, axis_name: str, causal: bool, scale: float,
    n_rep: int, bq: int, bk: int,
):
    """One device's whole ring pass. Grid: () — the ring loop is in-kernel.

    Per step: (1) neighbor barrier, (2) start the HBM→HBM RDMA of the current
    KV slot to the right neighbor's other slot, (3) stream (q block × kv
    block) tiles through VMEM updating the online-softmax state persisted in
    HBM scratch, (4) wait both RDMA semaphores. Causally-masked tiles are
    skipped before their DMA is issued.
    """
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    BH, Tl, D = q_hbm.shape
    my = my_ref[0]
    right = jax.lax.rem(my + 1, n)
    left = jax.lax.rem(my + n - 1, n)
    num_qb, num_kb = Tl // bq, Tl // bk

    def copy(src, dst):
        cp = pltpu.make_async_copy(src, dst, csem.at[0])
        cp.start()
        cp.wait()

    # entry rendezvous: both neighbors have entered the kernel (so their
    # ring-slot scratch is live) before any RDMA targets it. Data
    # dependencies bound inter-invocation skew to one kernel, so the global
    # barrier semaphore's counting cannot alias across invocations.
    barrier = pltpu.get_barrier_semaphore()
    pltpu.semaphore_signal(
        barrier, inc=1, device_id={axis_name: left},
        device_id_type=pltpu.DeviceIdType.MESH,
    )
    pltpu.semaphore_signal(
        barrier, inc=1, device_id={axis_name: right},
        device_id_type=pltpu.DeviceIdType.MESH,
    )
    pltpu.semaphore_wait(barrier, 2)

    # stage the local KV shard into ring slot 0
    copy(k_hbm, kbuf.at[0])
    copy(v_hbm, vbuf.at[0])

    for s in range(n):  # static unroll: n is the mesh-axis size
        cur, nxt = s % 2, (s + 1) % 2
        if s < n - 1:
            if s > 0:
                # the right neighbor freed its slot `nxt` (it finished
                # computing step s-1 on it and said so); a per-neighbor,
                # per-slot semaphore — unlike a counting barrier, a fast
                # LEFT neighbor's signals can never stand in for the right
                # neighbor's (data deps bound neighbor skew to one step, so
                # parity indexing cannot alias across rounds)
                pltpu.semaphore_wait(ready_sem.at[nxt], 1)
            rk = pltpu.make_async_remote_copy(
                src_ref=kbuf.at[cur], dst_ref=kbuf.at[nxt],
                send_sem=send_sem.at[cur, 0], recv_sem=recv_sem.at[nxt, 0],
                device_id={axis_name: right},
                device_id_type=pltpu.DeviceIdType.MESH,
            )
            rv = pltpu.make_async_remote_copy(
                src_ref=vbuf.at[cur], dst_ref=vbuf.at[nxt],
                send_sem=send_sem.at[cur, 1], recv_sem=recv_sem.at[nxt, 1],
                device_id={axis_name: right},
                device_id_type=pltpu.DeviceIdType.MESH,
            )
            rk.start()
            rv.start()

        src = jax.lax.rem(my - s + n, n)  # whose KV shard slot `cur` holds

        def qb_body(bh, qb):
            kvh = bh // n_rep
            copy(q_hbm.at[bh, pl.ds(qb * bq, bq)], qt)
            if s == 0:
                acct[:] = jnp.zeros_like(acct)
                mt[:] = jnp.full_like(mt, NEG_INF)
                lt[:] = jnp.zeros_like(lt)
            else:
                copy(acc_hbm.at[bh, pl.ds(qb * bq, bq)], acct)
                copy(m_hbm.at[bh, pl.ds(qb * bq, bq)], mt)
                copy(l_hbm.at[bh, pl.ds(qb * bq, bq)], lt)
            qv = qt[:].astype(jnp.float32) * scale
            q0 = my * Tl + qb * bq  # global position of this q block's row 0

            def kb_body(kb, _):
                k0 = src * Tl + kb * bk

                @pl.when(jnp.logical_or(not causal, k0 <= q0 + bq - 1))
                def _tile():
                    copy(kbuf.at[cur, kvh, pl.ds(kb * bk, bk)], kt)
                    copy(vbuf.at[cur, kvh, pl.ds(kb * bk, bk)], vt)
                    s_blk = jax.lax.dot_general(
                        qv, kt[:].astype(jnp.float32),
                        (((1,), (1,)), ((), ())),
                        preferred_element_type=jnp.float32,
                    )  # [bq, bk]
                    if causal:
                        q_pos = q0 + jax.lax.broadcasted_iota(jnp.int32, (bq, 1), 0)
                        k_pos = k0 + jax.lax.broadcasted_iota(jnp.int32, (1, bk), 1)
                        s_blk = jnp.where(q_pos >= k_pos, s_blk, NEG_INF)
                    m_prev = mt[:][:, :1]
                    l_prev = lt[:][:, :1]
                    m_new = jnp.maximum(m_prev, jnp.max(s_blk, axis=-1, keepdims=True))
                    alpha = jnp.exp(m_prev - m_new)
                    p = jnp.exp(s_blk - m_new)
                    if causal:  # fully-masked rows: keep contributions exactly 0
                        p = jnp.where(s_blk <= NEG_INF / 2, 0.0, p)
                    lt[:] = jnp.broadcast_to(
                        l_prev * alpha + jnp.sum(p, axis=-1, keepdims=True), lt.shape
                    )
                    mt[:] = jnp.broadcast_to(m_new, mt.shape)
                    acct[:] = acct[:] * alpha + jax.lax.dot_general(
                        p, vt[:].astype(jnp.float32),
                        (((1,), (0,)), ((), ())),
                        preferred_element_type=jnp.float32,
                    )

                return 0

            jax.lax.fori_loop(0, num_kb, kb_body, 0)
            if s == n - 1:
                ot[:] = (acct[:] / jnp.maximum(lt[:][:, :1], 1e-20)).astype(ot.dtype)
                copy(ot, o_hbm.at[bh, pl.ds(qb * bq, bq)])
            else:
                copy(acct, acc_hbm.at[bh, pl.ds(qb * bq, bq)])
                copy(mt, m_hbm.at[bh, pl.ds(qb * bq, bq)])
                copy(lt, l_hbm.at[bh, pl.ds(qb * bq, bq)])

        def run_qb_loop():
            jax.lax.fori_loop(
                0, BH * num_qb,
                lambda i, _: (qb_body(i // num_qb, i % num_qb), 0)[1], 0,
            )

        if causal and 0 < s < n - 1:
            # whole KV shard in the future ⇒ skip the entire state round-trip
            # for this step, not just the tile compute (s=0 always has src=my;
            # s=n-1 must run to write o)
            pl.when(src <= my)(run_qb_loop)
        else:
            run_qb_loop()

        if s < n - 1:
            rk.wait()
            rv.wait()
            # done reading slot `cur` — BOTH as compute input and as the
            # outgoing RDMA source (rk/rv.wait() above confirms the send
            # finished; signaling earlier would let the left neighbor
            # overwrite the buffer mid-send). Tell the LEFT neighbor (whose
            # step-s+1 RDMA targets our `cur`) it may overwrite it. No
            # circular wait: the ready-wait chain grounds out at s=0.
            pltpu.semaphore_signal(
                ready_sem.at[cur], inc=1, device_id={axis_name: left},
                device_id_type=pltpu.DeviceIdType.MESH,
            )


def _ring_fwd(q, k, v, axis_name: str, causal: bool, interpret: Any):
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    B, H, Tl, D = q.shape
    Hkv = k.shape[1]
    if H % Hkv:
        raise ValueError(f"n_heads {H} must be divisible by n_kv_heads {Hkv}")
    n_rep = H // Hkv
    n = jax.lax.axis_size(axis_name)
    my = jax.lax.axis_index(axis_name)
    scale = D ** -0.5
    bq = min(256, Tl)
    bk = min(256, Tl)
    if Tl % bq or Tl % bk:
        raise ValueError(f"per-device sequence {Tl} must be a multiple of {bq}")
    qf = q.reshape(B * H, Tl, D)
    kf = k.reshape(B * Hkv, Tl, D)
    vf = v.reshape(B * Hkv, Tl, D)

    kernel = functools.partial(
        _ring_fwd_kernel, n=n, axis_name=axis_name, causal=causal, scale=scale,
        n_rep=n_rep, bq=bq, bk=bk,
    )
    hbm = pltpu.MemorySpace.HBM
    out = pl.pallas_call(
        kernel,
        in_specs=[
            pl.BlockSpec(memory_space=pltpu.MemorySpace.SMEM),
            pl.BlockSpec(memory_space=hbm),
            pl.BlockSpec(memory_space=hbm),
            pl.BlockSpec(memory_space=hbm),
        ],
        out_specs=pl.BlockSpec(memory_space=hbm),
        out_shape=jax.ShapeDtypeStruct((B * H, Tl, D), q.dtype),
        scratch_shapes=[
            hbm((2, B * Hkv, Tl, D), k.dtype),            # ring KV slots
            hbm((2, B * Hkv, Tl, D), v.dtype),
            hbm((B * H, Tl, D), jnp.float32),             # online-softmax state
            hbm((B * H, Tl, _STAT_LANES), jnp.float32),
            hbm((B * H, Tl, _STAT_LANES), jnp.float32),
            pltpu.MemorySpace.VMEM((bq, D), q.dtype),     # tiles
            pltpu.MemorySpace.VMEM((bk, D), k.dtype),
            pltpu.MemorySpace.VMEM((bk, D), v.dtype),
            pltpu.MemorySpace.VMEM((bq, D), jnp.float32),
            pltpu.MemorySpace.VMEM((bq, _STAT_LANES), jnp.float32),
            pltpu.MemorySpace.VMEM((bq, _STAT_LANES), jnp.float32),
            pltpu.MemorySpace.VMEM((bq, D), q.dtype),
            pltpu.SemaphoreType.DMA((1,)),
            pltpu.SemaphoreType.DMA((2, 2)),
            pltpu.SemaphoreType.DMA((2, 2)),
            pltpu.SemaphoreType.REGULAR((2,)),    # per-slot "free" acks
        ],
        compiler_params=pltpu.CompilerParams(collective_id=RING_ATTENTION_COLLECTIVE_ID),
        interpret=interpret if interpret is not None else default_interpret(),
    )(jnp.full((1,), my, jnp.int32), qf, kf, vf)
    return out.reshape(B, H, Tl, D)


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5))
def ring_attention_pallas(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    axis_name: str = "context",
    causal: bool = True,
    interpret: Any = None,
) -> jax.Array:
    """Ring attention with the KV rotation as in-kernel remote DMA.

    Must run inside ``shard_map`` with the sequence dim sharded over
    ``axis_name``; per-shard shapes q [B, H, T_local, D], k/v
    [B, Hkv, T_local, D] with H % Hkv == 0 (GQA stays at Hkv width on the
    wire). ``interpret`` accepts ``pltpu.InterpretParams`` for the
    emulated-RDMA CPU path; None defers to ``TONY_PALLAS_INTERPRET``.
    """
    return _ring_fwd(q, k, v, axis_name, causal, interpret)


def _ring_vjp_fwd(q, k, v, axis_name, causal, interpret):
    return _ring_fwd(q, k, v, axis_name, causal, interpret), (q, k, v)


def _ring_vjp_bwd(axis_name, causal, interpret, res, g):
    # backward through the XLA ring (same schedule, compiler-scheduled
    # collectives): recompute-from-inputs, the standard flash-bwd trade
    from tony_tpu.ops.attention import repeat_kv
    from tony_tpu.parallel.context import ring_attention

    q, k, v = res
    n_rep = q.shape[1] // k.shape[1]

    def ref(q, k, v):
        return ring_attention(
            q, repeat_kv(k, n_rep), repeat_kv(v, n_rep),
            axis_name=axis_name, causal=causal,
        )

    _, vjp = jax.vjp(ref, q, k, v)
    return vjp(g)


ring_attention_pallas.defvjp(_ring_vjp_fwd, _ring_vjp_bwd)
