"""Pallas remote-DMA ring attention: the hand-overlapped CP data plane.

The XLA implementation (parallel/context.ring_attention) expresses the ring
as ``lax.scan`` + ``ppermute`` and leaves transfer/compute overlap to the
compiler. This module is the same blockwise-softmax schedule written as ONE
Pallas kernel per device: KV shards travel the ``context``-axis ring as
inter-chip RDMA (``make_async_remote_copy`` over ICI) between **HBM-resident
double-buffered slots**, while the kernel overlaps each transfer with the
flash-attention math on the slot it already holds — the TPU analog of the
reference's NCCL-ring data plane, which lived inside user frameworks
(SURVEY.md §2.6), built per the Pallas guide's ring-collective pattern.

VMEM discipline: only tiles pass through VMEM (q/k/v blocks and the f32
softmax state for one q block), so per-device shard size is bounded by HBM,
not VMEM, and KV stays at Hkv width end to end (GQA-native — q heads alias
onto kv heads inside the compute loop, never broadcast).

Differentiable end-to-end in-kernel: the custom VJP's backward is its own
remote-DMA ring kernel (``_ring_bwd_kernel``) — dk/dv partial sums ride the
ring alongside their KV shard, each device adds its local contribution
(recomputing p blockwise from q/k/lse), and a final rotation delivers each
shard's finished gradients home. No XLA-ring fallback anywhere; the kernel
drops into training models via ``LlamaConfig(cp_impl="pallas")``.

Validated in TPU-interpret mode (which emulates RDMA + semaphores across
shard_map devices, with race detection) on a virtual CPU mesh; the real-ICI
path uses the same code with ``interpret=None`` on a physical slice.
"""

from __future__ import annotations

import functools
import os
from typing import Any

import jax
import jax.numpy as jnp

from tony_tpu.compat import axis_size, tpu_compiler_params, tpu_interpret_params
from tony_tpu.ops.attention import NEG_INF, _STAT_LANES

# Registry of Pallas collective_ids in this program. A collective_id names the
# cross-device barrier-semaphore set; two concurrently-live collective kernels
# sharing an id would alias barrier counts and silently hang. Reserve ids here.
RING_ATTENTION_COLLECTIVE_ID = 7      # forward ring kernel
RING_ATTENTION_BWD_COLLECTIVE_ID = 8  # backward ring kernel (may overlap fwd
                                      # of the next microbatch under pipelining)
# next free id: 9


def default_interpret():
    """InterpretParams when the env asks for emulated kernels, else False
    (same TONY_PALLAS_INTERPRET contract as ops/attention.py)."""
    if os.environ.get("TONY_PALLAS_INTERPRET", "") == "1":
        return tpu_interpret_params()
    return False


# ring block caps, env-tunable like the flash kernels' TONY_FLASH_BQ/BK.
# The flash ladder measured bk 512 > 256 on every single-chip preset (r3,
# BASELINE.md); the ring's KV block also sets the per-rotation DMA slab, and
# without multi-chip hardware the 256 default stays unvalidated — retune
# TONY_RING_BQ/BK on a real slice.
_RING_BQ = int(os.environ.get("TONY_RING_BQ", "256"))
_RING_BK = int(os.environ.get("TONY_RING_BK", "256"))
for _name, _b in (("TONY_RING_BQ", _RING_BQ), ("TONY_RING_BK", _RING_BK)):
    if _b < 8:  # fail at import, not deep inside a shard_map trace; the value
        # is a CAP on the block search, so any integer ≥ 8 is usable
        raise ValueError(f"{_name}={_b}: ring block caps must be >= 8")


def _pick_block(Tl: int, cap: int = 256) -> int:
    """Largest divisor of the per-device sequence that is a multiple of 8
    and ≤ cap — no hard error for short shards (VERDICT r2 weak #6)."""
    for b in range(min(cap, Tl), 7, -1):
        if Tl % b == 0 and b % 8 == 0:
            return b
    raise ValueError(
        f"per-device sequence {Tl} has no block size (multiple of 8, <= {cap})"
    )


def _ring_fwd_kernel(
    my_ref, q_hbm, k_hbm, v_hbm, *rest,
    n: int, axis_name: str, causal: bool, scale: float,
    n_rep: int, bq: int, bk: int, window: int, has_seg: bool, H: int,
):
    """One device's whole ring pass. Grid: () — the ring loop is in-kernel.

    Per step: (1) neighbor barrier, (2) start the HBM→HBM RDMA of the current
    KV slot to the right neighbor's other slot, (3) stream (q block × kv
    block) tiles through VMEM updating the online-softmax state persisted in
    HBM scratch, (4) wait both RDMA semaphores. Causally-masked tiles are
    skipped before their DMA is issued; a ``window`` adds the symmetric
    below-band skip (SWA), and packed ``segment_ids`` confine attention
    within segments (the GLOBAL segment table rides along replicated — ids
    are tiny next to KV — so no extra ring traffic).
    """
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    if has_seg:
        segq_hbm, segk_hbm = rest[0], rest[1]
        (o_hbm, lse_hbm, kbuf, vbuf, acc_hbm, m_hbm, l_hbm,
         qt, kt, vt, acct, mt, lt, ot, segqt, segkt,
         csem, send_sem, recv_sem, ready_sem) = rest[2:]
    else:
        segq_hbm = segk_hbm = segqt = segkt = None
        (o_hbm, lse_hbm, kbuf, vbuf, acc_hbm, m_hbm, l_hbm,
         qt, kt, vt, acct, mt, lt, ot,
         csem, send_sem, recv_sem, ready_sem) = rest

    BH, Tl, D = q_hbm.shape
    my = my_ref[0]
    right = jax.lax.rem(my + 1, n)
    left = jax.lax.rem(my + n - 1, n)
    num_qb, num_kb = Tl // bq, Tl // bk

    def copy(src, dst):
        cp = pltpu.make_async_copy(src, dst, csem.at[0])
        cp.start()
        cp.wait()

    # entry rendezvous: both neighbors have entered the kernel (so their
    # ring-slot scratch is live) before any RDMA targets it. Data
    # dependencies bound inter-invocation skew to one kernel, so the global
    # barrier semaphore's counting cannot alias across invocations.
    barrier = pltpu.get_barrier_semaphore()
    pltpu.semaphore_signal(
        barrier, inc=1, device_id={axis_name: left},
        device_id_type=pltpu.DeviceIdType.MESH,
    )
    pltpu.semaphore_signal(
        barrier, inc=1, device_id={axis_name: right},
        device_id_type=pltpu.DeviceIdType.MESH,
    )
    pltpu.semaphore_wait(barrier, 2)

    # stage the local KV shard into ring slot 0
    copy(k_hbm, kbuf.at[0])
    copy(v_hbm, vbuf.at[0])

    for s in range(n):  # static unroll: n is the mesh-axis size
        cur, nxt = s % 2, (s + 1) % 2
        if s < n - 1:
            if s > 0:
                # the right neighbor freed its slot `nxt` (it finished
                # computing step s-1 on it and said so); a per-neighbor,
                # per-slot semaphore — unlike a counting barrier, a fast
                # LEFT neighbor's signals can never stand in for the right
                # neighbor's (data deps bound neighbor skew to one step, so
                # parity indexing cannot alias across rounds)
                pltpu.semaphore_wait(ready_sem.at[nxt], 1)
            rk = pltpu.make_async_remote_copy(
                src_ref=kbuf.at[cur], dst_ref=kbuf.at[nxt],
                send_sem=send_sem.at[cur, 0], recv_sem=recv_sem.at[nxt, 0],
                device_id={axis_name: right},
                device_id_type=pltpu.DeviceIdType.MESH,
            )
            rv = pltpu.make_async_remote_copy(
                src_ref=vbuf.at[cur], dst_ref=vbuf.at[nxt],
                send_sem=send_sem.at[cur, 1], recv_sem=recv_sem.at[nxt, 1],
                device_id={axis_name: right},
                device_id_type=pltpu.DeviceIdType.MESH,
            )
            rk.start()
            rv.start()

        src = jax.lax.rem(my - s + n, n)  # whose KV shard slot `cur` holds

        def qb_body(bh, qb):
            kvh = bh // n_rep
            copy(q_hbm.at[bh, pl.ds(qb * bq, bq)], qt)
            if has_seg:
                copy(segq_hbm.at[bh // H, pl.ds(qb * bq, bq)], segqt)
            if s == 0:
                acct[:] = jnp.zeros_like(acct)
                mt[:] = jnp.full_like(mt, NEG_INF)
                lt[:] = jnp.zeros_like(lt)
            else:
                copy(acc_hbm.at[bh, pl.ds(qb * bq, bq)], acct)
                copy(m_hbm.at[bh, pl.ds(qb * bq, bq)], mt)
                copy(l_hbm.at[bh, pl.ds(qb * bq, bq)], lt)
            qv = qt[:].astype(jnp.float32) * scale
            q0 = my * Tl + qb * bq  # global position of this q block's row 0

            def kb_body(kb, _):
                k0 = src * Tl + kb * bk

                ok = jnp.bool_(True)
                if causal:
                    ok = jnp.logical_and(ok, k0 <= q0 + bq - 1)
                if window > 0:  # whole tile below the band ⇒ skip its DMA
                    ok = jnp.logical_and(ok, k0 + bk - 1 >= q0 - window + 1)

                @pl.when(ok)
                def _tile():
                    copy(kbuf.at[cur, kvh, pl.ds(kb * bk, bk)], kt)
                    copy(vbuf.at[cur, kvh, pl.ds(kb * bk, bk)], vt)
                    s_blk = jax.lax.dot_general(
                        qv, kt[:].astype(jnp.float32),
                        (((1,), (1,)), ((), ())),
                        preferred_element_type=jnp.float32,
                    )  # [bq, bk]
                    masked = causal or window > 0 or has_seg
                    if causal or window > 0:
                        q_pos = q0 + jax.lax.broadcasted_iota(jnp.int32, (bq, 1), 0)
                        k_pos = k0 + jax.lax.broadcasted_iota(jnp.int32, (1, bk), 1)
                        keep = jnp.bool_(True)
                        if causal:
                            keep = jnp.logical_and(keep, q_pos >= k_pos)
                        if window > 0:
                            keep = jnp.logical_and(keep, k_pos > q_pos - window)
                        s_blk = jnp.where(keep, s_blk, NEG_INF)
                    if has_seg:
                        copy(
                            segk_hbm.at[bh // H, :, pl.ds(src * Tl + kb * bk, bk)],
                            segkt,
                        )
                        s_blk = jnp.where(
                            segqt[:][:, :1] == segkt[:][:1, :], s_blk, NEG_INF
                        )
                    m_prev = mt[:][:, :1]
                    l_prev = lt[:][:, :1]
                    m_new = jnp.maximum(m_prev, jnp.max(s_blk, axis=-1, keepdims=True))
                    alpha = jnp.exp(m_prev - m_new)
                    p = jnp.exp(s_blk - m_new)
                    if masked:  # fully-masked rows: keep contributions exactly 0
                        p = jnp.where(s_blk <= NEG_INF / 2, 0.0, p)
                    lt[:] = jnp.broadcast_to(
                        l_prev * alpha + jnp.sum(p, axis=-1, keepdims=True), lt.shape
                    )
                    mt[:] = jnp.broadcast_to(m_new, mt.shape)
                    acct[:] = acct[:] * alpha + jax.lax.dot_general(
                        p, vt[:].astype(jnp.float32),
                        (((1,), (0,)), ((), ())),
                        preferred_element_type=jnp.float32,
                    )

                return 0

            jax.lax.fori_loop(0, num_kb, kb_body, 0)
            if s == n - 1:
                ot[:] = (acct[:] / jnp.maximum(lt[:][:, :1], 1e-20)).astype(ot.dtype)
                copy(ot, o_hbm.at[bh, pl.ds(qb * bq, bq)])
                # lse residual for the ring backward (lane-replicated)
                mt[:] = mt[:] + jnp.log(jnp.maximum(lt[:], 1e-20))
                copy(mt, lse_hbm.at[bh, pl.ds(qb * bq, bq)])
            else:
                copy(acct, acc_hbm.at[bh, pl.ds(qb * bq, bq)])
                copy(mt, m_hbm.at[bh, pl.ds(qb * bq, bq)])
                copy(lt, l_hbm.at[bh, pl.ds(qb * bq, bq)])

        def run_qb_loop():
            jax.lax.fori_loop(
                0, BH * num_qb,
                lambda i, _: (qb_body(i // num_qb, i % num_qb), 0)[1], 0,
            )

        if causal and 0 < s < n - 1:
            # whole KV shard in the future ⇒ skip the entire state round-trip
            # for this step, not just the tile compute (s=0 always has src=my;
            # s=n-1 must run to write o). A window also skips shards wholly
            # BELOW the band (k entirely before my earliest in-window row).
            needed = src <= my
            if window > 0:
                needed = jnp.logical_and(
                    needed, src * Tl + Tl - 1 >= my * Tl - window + 1
                )
            pl.when(needed)(run_qb_loop)
        else:
            run_qb_loop()

        if s < n - 1:
            rk.wait()
            rv.wait()
            # done reading slot `cur` — BOTH as compute input and as the
            # outgoing RDMA source (rk/rv.wait() above confirms the send
            # finished; signaling earlier would let the left neighbor
            # overwrite the buffer mid-send). Tell the LEFT neighbor (whose
            # step-s+1 RDMA targets our `cur`) it may overwrite it. No
            # circular wait: the ready-wait chain grounds out at s=0.
            pltpu.semaphore_signal(
                ready_sem.at[cur], inc=1, device_id={axis_name: left},
                device_id_type=pltpu.DeviceIdType.MESH,
            )

    if n > 1:
        # drain the right neighbor's final free-signal (sent at its step
        # n-2, consumed by no RDMA): semaphores must be zero at kernel exit
        pltpu.semaphore_wait(ready_sem.at[(n - 2) % 2], 1)


def _seg_layouts(segment_ids, axis_name):
    """Local seg [B, Tl] → (segq [B, Tl, LANES] f32 local, segk
    [B, LANES, T_global] f32 — the all-gathered global table; ids are tiny
    next to KV, so replicating beats adding them to the ring payload)."""
    segf = segment_ids.astype(jnp.float32)
    segq = jnp.broadcast_to(segf[:, :, None], (*segf.shape, _STAT_LANES))
    gathered = jax.lax.all_gather(segf, axis_name)            # [n, B, Tl]
    full = jnp.moveaxis(gathered, 0, 1).reshape(segf.shape[0], -1)  # [B, T]
    segk = jnp.broadcast_to(full[:, None, :], (full.shape[0], _STAT_LANES, full.shape[1]))
    return segq, segk


def _ring_fwd(q, k, v, axis_name: str, causal: bool, interpret: Any,
              window: int = 0, segment_ids=None):
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    B, H, Tl, D = q.shape
    Hkv = k.shape[1]
    if H % Hkv:
        raise ValueError(f"n_heads {H} must be divisible by n_kv_heads {Hkv}")
    n_rep = H // Hkv
    n = axis_size(axis_name)
    my = jax.lax.axis_index(axis_name)
    scale = D ** -0.5
    bq = _pick_block(Tl, _RING_BQ)
    bk = _pick_block(Tl, _RING_BK)
    has_seg = segment_ids is not None
    qf = q.reshape(B * H, Tl, D)
    kf = k.reshape(B * Hkv, Tl, D)
    vf = v.reshape(B * Hkv, Tl, D)

    kernel = functools.partial(
        _ring_fwd_kernel, n=n, axis_name=axis_name, causal=causal, scale=scale,
        n_rep=n_rep, bq=bq, bk=bk, window=window, has_seg=has_seg, H=H,
    )
    hbm = pltpu.MemorySpace.HBM
    operands = [jnp.full((1,), my, jnp.int32), qf, kf, vf]
    in_specs = [
        pl.BlockSpec(memory_space=pltpu.MemorySpace.SMEM),
        pl.BlockSpec(memory_space=hbm),
        pl.BlockSpec(memory_space=hbm),
        pl.BlockSpec(memory_space=hbm),
    ]
    seg_tiles = []
    if has_seg:
        segq, segk = _seg_layouts(segment_ids, axis_name)
        operands += [segq, segk]
        in_specs += [pl.BlockSpec(memory_space=hbm), pl.BlockSpec(memory_space=hbm)]
        seg_tiles = [
            pltpu.MemorySpace.VMEM((bq, _STAT_LANES), jnp.float32),
            pltpu.MemorySpace.VMEM((_STAT_LANES, bk), jnp.float32),
        ]
    out, lse = pl.pallas_call(
        kernel,
        in_specs=in_specs,
        out_specs=[pl.BlockSpec(memory_space=hbm), pl.BlockSpec(memory_space=hbm)],
        out_shape=[
            jax.ShapeDtypeStruct((B * H, Tl, D), q.dtype),
            jax.ShapeDtypeStruct((B * H, Tl, _STAT_LANES), jnp.float32),
        ],
        scratch_shapes=[
            hbm((2, B * Hkv, Tl, D), k.dtype),            # ring KV slots
            hbm((2, B * Hkv, Tl, D), v.dtype),
            hbm((B * H, Tl, D), jnp.float32),             # online-softmax state
            hbm((B * H, Tl, _STAT_LANES), jnp.float32),
            hbm((B * H, Tl, _STAT_LANES), jnp.float32),
            pltpu.MemorySpace.VMEM((bq, D), q.dtype),     # tiles
            pltpu.MemorySpace.VMEM((bk, D), k.dtype),
            pltpu.MemorySpace.VMEM((bk, D), v.dtype),
            pltpu.MemorySpace.VMEM((bq, D), jnp.float32),
            pltpu.MemorySpace.VMEM((bq, _STAT_LANES), jnp.float32),
            pltpu.MemorySpace.VMEM((bq, _STAT_LANES), jnp.float32),
            pltpu.MemorySpace.VMEM((bq, D), q.dtype),
            *seg_tiles,
            pltpu.SemaphoreType.DMA((1,)),
            pltpu.SemaphoreType.DMA((2, 2)),
            pltpu.SemaphoreType.DMA((2, 2)),
            pltpu.SemaphoreType.REGULAR((2,)),    # per-slot "free" acks
        ],
        compiler_params=tpu_compiler_params(collective_id=RING_ATTENTION_COLLECTIVE_ID),
        interpret=interpret if interpret is not None else default_interpret(),
    )(*operands)
    return out.reshape(B, H, Tl, D), lse.reshape(B, H, Tl, _STAT_LANES)


def _ring_bwd_kernel(
    my_ref, q_hbm, k_hbm, v_hbm, do_hbm, lse_hbm, delta_hbm, *rest,
    n: int, axis_name: str, causal: bool, scale: float,
    n_rep: int, bq: int, bk: int, window: int, has_seg: bool, H: int,
    slab: int,
):
    """Ring-attention backward as one remote-DMA ring pass per device.

    The rotating payload is (k, v, dk_acc, dv_acc): each KV shard carries its
    f32 dk/dv partial sums around the ring, every device adds its local
    q-block contributions (recomputing p blockwise from q, k, lse — the
    flash-backward trade), dq accumulates locally in HBM, and after the last
    compute step ONE extra rotation delivers each shard's finished dk/dv to
    its home device's output refs. KV shards wholly in this device's causal
    future skip compute (their accumulators still ride the ring).
    """
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    if has_seg:
        segq_hbm, segk_hbm = rest[0], rest[1]
        (dq_hbm, dk_hbm, dv_hbm,
         kbuf, vbuf, dkbuf, dvbuf,
         qt, kt, vt, dot, lset, deltat, dqt, dks, dvs, segqt, segkt,
         csem, send_sem, recv_sem, ready_sem, fin_sem_s, fin_sem_r) = rest[2:]
    else:
        segq_hbm = segk_hbm = segqt = segkt = None
        (dq_hbm, dk_hbm, dv_hbm,
         kbuf, vbuf, dkbuf, dvbuf,
         qt, kt, vt, dot, lset, deltat, dqt, dks, dvs,
         csem, send_sem, recv_sem, ready_sem, fin_sem_s, fin_sem_r) = rest

    BH, Tl, D = q_hbm.shape
    BHkv = k_hbm.shape[0]
    my = my_ref[0]
    right = jax.lax.rem(my + 1, n)
    left = jax.lax.rem(my + n - 1, n)
    num_qb, num_kb = Tl // bq, Tl // bk

    def copy(src, dst):
        cp = pltpu.make_async_copy(src, dst, csem.at[0])
        cp.start()
        cp.wait()

    barrier = pltpu.get_barrier_semaphore()
    pltpu.semaphore_signal(
        barrier, inc=1, device_id={axis_name: left},
        device_id_type=pltpu.DeviceIdType.MESH,
    )
    pltpu.semaphore_signal(
        barrier, inc=1, device_id={axis_name: right},
        device_id_type=pltpu.DeviceIdType.MESH,
    )
    pltpu.semaphore_wait(barrier, 2)

    # zero the local dq accumulator
    dqt[:] = jnp.zeros_like(dqt)

    def zero_dq(i, _):
        copy(dqt, dq_hbm.at[i // num_qb, pl.ds((i % num_qb) * bq, bq)])
        return 0

    jax.lax.fori_loop(0, BH * num_qb, zero_dq, 0)

    # stage the local KV shard into ring slot 0; its dk/dv start at zero
    copy(k_hbm, kbuf.at[0])
    copy(v_hbm, vbuf.at[0])
    dks[:] = jnp.zeros_like(dks)
    dvs[:] = jnp.zeros_like(dvs)
    n_sl = Tl // slab

    def zero_dkv(i, _):
        copy(dks, dkbuf.at[0, i // n_sl, pl.ds((i % n_sl) * slab, slab)])
        copy(dvs, dvbuf.at[0, i // n_sl, pl.ds((i % n_sl) * slab, slab)])
        return 0

    jax.lax.fori_loop(0, BHkv * n_sl, zero_dkv, 0)

    for s in range(n):
        cur, nxt = s % 2, (s + 1) % 2
        src = jax.lax.rem(my - s + n, n)  # whose KV shard slot `cur` holds

        # kv is read-only: its RDMA can overlap this step's compute. dk/dv
        # must ship AFTER our contribution is added — started post-compute.
        if s < n - 1:
            if s > 0:
                pltpu.semaphore_wait(ready_sem.at[nxt], 1)
            rk = pltpu.make_async_remote_copy(
                src_ref=kbuf.at[cur], dst_ref=kbuf.at[nxt],
                send_sem=send_sem.at[cur, 0], recv_sem=recv_sem.at[nxt, 0],
                device_id={axis_name: right},
                device_id_type=pltpu.DeviceIdType.MESH,
            )
            rv = pltpu.make_async_remote_copy(
                src_ref=vbuf.at[cur], dst_ref=vbuf.at[nxt],
                send_sem=send_sem.at[cur, 1], recv_sem=recv_sem.at[nxt, 1],
                device_id={axis_name: right},
                device_id_type=pltpu.DeviceIdType.MESH,
            )
            rk.start()
            rv.start()

        num_slabs = Tl // slab
        kb_per_slab = slab // bk

        def slab_body(bh, sl):
            # a SLAB of the riding dk/dv accumulators lives in VMEM
            # (dks/dvs scratch, size bounded by the slab — NOT by Tl, so
            # long shards can't blow the VMEM budget): inner tiles
            # accumulate with ZERO HBM read-modify-writes; dq is
            # loaded/stored once per (q tile, slab) instead of once per
            # (q tile × kv tile) — the r2 "serial dq RMW"
            s_lo = sl * slab
            copy(dkbuf.at[cur, bh, pl.ds(s_lo, slab)], dks)
            copy(dvbuf.at[cur, bh, pl.ds(s_lo, slab)], dvs)

            def qb_body(g, qb):
                qh = bh * n_rep + g
                q0 = my * Tl + qb * bq
                # whole-q-tile skip: nothing in this slab is visible to it
                q_ok = jnp.bool_(True)
                if causal:
                    q_ok = jnp.logical_and(q_ok, src * Tl + s_lo <= q0 + bq - 1)
                if window > 0:
                    q_ok = jnp.logical_and(
                        q_ok, src * Tl + s_lo + slab - 1 >= q0 - window + 1
                    )

                @pl.when(q_ok)
                def _qtile():
                    copy(q_hbm.at[qh, pl.ds(qb * bq, bq)], qt)
                    copy(do_hbm.at[qh, pl.ds(qb * bq, bq)], dot)
                    copy(lse_hbm.at[qh, pl.ds(qb * bq, bq)], lset)
                    copy(delta_hbm.at[qh, pl.ds(qb * bq, bq)], deltat)
                    copy(dq_hbm.at[qh, pl.ds(qb * bq, bq)], dqt)
                    if has_seg:
                        copy(segq_hbm.at[qh // H, pl.ds(qb * bq, bq)], segqt)
                    qv = qt[:].astype(jnp.float32)
                    dov = dot[:].astype(jnp.float32)

                    def kb_body(kb, _):
                        k0 = src * Tl + s_lo + kb * bk
                        ok = jnp.bool_(True)
                        if causal:
                            ok = jnp.logical_and(ok, k0 <= q0 + bq - 1)
                        if window > 0:
                            ok = jnp.logical_and(ok, k0 + bk - 1 >= q0 - window + 1)

                        @pl.when(ok)
                        def _tile():
                            copy(kbuf.at[cur, bh, pl.ds(s_lo + kb * bk, bk)], kt)
                            copy(vbuf.at[cur, bh, pl.ds(s_lo + kb * bk, bk)], vt)
                            if has_seg:
                                copy(
                                    segk_hbm.at[
                                        bh // (BHkv * H // BH), :,
                                        pl.ds(src * Tl + s_lo + kb * bk, bk),
                                    ],
                                    segkt,
                                )
                            kv = kt[:].astype(jnp.float32)
                            vv = vt[:].astype(jnp.float32)
                            k_pos = k0 + jax.lax.broadcasted_iota(jnp.int32, (1, bk), 1)
                            s_blk = scale * jax.lax.dot_general(
                                qv, kv, (((1,), (1,)), ((), ())),
                                preferred_element_type=jnp.float32,
                            )
                            if causal or window > 0:
                                q_pos = q0 + jax.lax.broadcasted_iota(jnp.int32, (bq, 1), 0)
                                keep = jnp.bool_(True)
                                if causal:
                                    keep = jnp.logical_and(keep, q_pos >= k_pos)
                                if window > 0:
                                    keep = jnp.logical_and(keep, k_pos > q_pos - window)
                                s_blk = jnp.where(keep, s_blk, NEG_INF)
                            if has_seg:
                                s_blk = jnp.where(
                                    segqt[:][:, :1] == segkt[:][:1, :], s_blk, NEG_INF
                                )
                            p = jnp.exp(s_blk - lset[:][:, :1])
                            dp = jax.lax.dot_general(
                                dov, vv, (((1,), (1,)), ((), ())),
                                preferred_element_type=jnp.float32,
                            )
                            ds = p * (dp - deltat[:][:, :1])
                            dvs[pl.ds(kb * bk, bk)] += jax.lax.dot_general(  # p^T @ do
                                p, dov, (((0,), (0,)), ((), ())),
                                preferred_element_type=jnp.float32,
                            )
                            dks[pl.ds(kb * bk, bk)] += scale * jax.lax.dot_general(
                                ds, qv, (((0,), (0,)), ((), ())),            # ds^T @ q
                                preferred_element_type=jnp.float32,
                            )
                            dqt[:] += scale * jax.lax.dot_general(           # ds @ k
                                ds, kv, (((1,), (0,)), ((), ())),
                                preferred_element_type=jnp.float32,
                            )

                        return 0

                    jax.lax.fori_loop(0, kb_per_slab, kb_body, 0)
                    copy(dqt, dq_hbm.at[qh, pl.ds(qb * bq, bq)])

                return 0

            jax.lax.fori_loop(
                0, n_rep * num_qb,
                lambda i, _: (qb_body(i // num_qb, i % num_qb), 0)[1], 0,
            )
            copy(dks, dkbuf.at[cur, bh, pl.ds(s_lo, slab)])
            copy(dvs, dvbuf.at[cur, bh, pl.ds(s_lo, slab)])
            return 0

        def run_kb_loop():
            jax.lax.fori_loop(
                0, BHkv * num_slabs,
                lambda i, _: (slab_body(i // num_slabs, i % num_slabs), 0)[1], 0,
            )

        if causal and s > 0:
            # whole shard in this device's causal future ⇒ nothing to add
            # (the accumulators still ride the ring untouched); with a
            # window also skip shards wholly below the band
            needed = src <= my
            if window > 0:
                needed = jnp.logical_and(
                    needed, src * Tl + Tl - 1 >= my * Tl - window + 1
                )
            pl.when(needed)(run_kb_loop)
        else:
            run_kb_loop()

        if s < n - 1:
            # ship the updated dk/dv accumulators after compute
            rdk = pltpu.make_async_remote_copy(
                src_ref=dkbuf.at[cur], dst_ref=dkbuf.at[nxt],
                send_sem=send_sem.at[cur, 2], recv_sem=recv_sem.at[nxt, 2],
                device_id={axis_name: right},
                device_id_type=pltpu.DeviceIdType.MESH,
            )
            rdv = pltpu.make_async_remote_copy(
                src_ref=dvbuf.at[cur], dst_ref=dvbuf.at[nxt],
                send_sem=send_sem.at[cur, 3], recv_sem=recv_sem.at[nxt, 3],
                device_id={axis_name: right},
                device_id_type=pltpu.DeviceIdType.MESH,
            )
            rdk.start()
            rdv.start()
            rk.wait()
            rv.wait()
            rdk.wait()
            rdv.wait()
            pltpu.semaphore_signal(
                ready_sem.at[cur], inc=1, device_id={axis_name: left},
                device_id_type=pltpu.DeviceIdType.MESH,
            )

    if n > 1:
        # drain the right neighbor's final free-signal (same reason as the
        # forward kernel: zero semaphores at exit)
        pltpu.semaphore_wait(ready_sem.at[(n - 2) % 2], 1)

    # final rotation: shard my+1's finished dk/dv sits in our last slot —
    # deliver it straight into the right neighbor's output refs
    last = (n - 1) % 2
    fdk = pltpu.make_async_remote_copy(
        src_ref=dkbuf.at[last], dst_ref=dk_hbm,
        send_sem=fin_sem_s.at[0], recv_sem=fin_sem_r.at[0],
        device_id={axis_name: right},
        device_id_type=pltpu.DeviceIdType.MESH,
    )
    fdv = pltpu.make_async_remote_copy(
        src_ref=dvbuf.at[last], dst_ref=dv_hbm,
        send_sem=fin_sem_s.at[1], recv_sem=fin_sem_r.at[1],
        device_id={axis_name: right},
        device_id_type=pltpu.DeviceIdType.MESH,
    )
    fdk.start()
    fdv.start()
    fdk.wait()
    fdv.wait()


def _ring_bwd(q, k, v, o, lse, do, axis_name: str, causal: bool, interpret: Any,
              window: int = 0, segment_ids=None):
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    B, H, Tl, D = q.shape
    Hkv = k.shape[1]
    n_rep = H // Hkv
    n = axis_size(axis_name)
    my = jax.lax.axis_index(axis_name)
    scale = D ** -0.5
    bq = _pick_block(Tl, _RING_BQ)
    bk = _pick_block(Tl, _RING_BK)
    has_seg = segment_ids is not None
    qf = q.reshape(B * H, Tl, D)
    kf = k.reshape(B * Hkv, Tl, D)
    vf = v.reshape(B * Hkv, Tl, D)
    dof = do.reshape(B * H, Tl, D)
    lsef = lse.reshape(B * H, Tl, _STAT_LANES)
    delta = jnp.sum(
        dof.astype(jnp.float32) * o.reshape(B * H, Tl, D).astype(jnp.float32), axis=-1
    )
    delta = jnp.broadcast_to(delta[:, :, None], (B * H, Tl, _STAT_LANES))

    # slab: largest bk-multiple divisor of Tl within a ~4 MB f32 budget —
    # the VMEM accumulator footprint is bounded by the slab, not by Tl
    budget_rows = max(bk, (4 * 2 ** 20) // (D * 4) // bk * bk)
    slab = bk
    for s_cand in range(min(Tl, budget_rows), bk - 1, -bk):
        if Tl % s_cand == 0:
            slab = s_cand
            break
    kernel = functools.partial(
        _ring_bwd_kernel, n=n, axis_name=axis_name, causal=causal, scale=scale,
        n_rep=n_rep, bq=bq, bk=bk, window=window, has_seg=has_seg, H=H,
        slab=slab,
    )
    hbm = pltpu.MemorySpace.HBM
    operands = [jnp.full((1,), my, jnp.int32), qf, kf, vf, dof, lsef, delta]
    in_specs = [
        pl.BlockSpec(memory_space=pltpu.MemorySpace.SMEM),
        pl.BlockSpec(memory_space=hbm),
        pl.BlockSpec(memory_space=hbm),
        pl.BlockSpec(memory_space=hbm),
        pl.BlockSpec(memory_space=hbm),
        pl.BlockSpec(memory_space=hbm),
        pl.BlockSpec(memory_space=hbm),
    ]
    seg_tiles = []
    if has_seg:
        segq, segk = _seg_layouts(segment_ids, axis_name)
        operands += [segq, segk]
        in_specs += [pl.BlockSpec(memory_space=hbm), pl.BlockSpec(memory_space=hbm)]
        seg_tiles = [
            pltpu.MemorySpace.VMEM((bq, _STAT_LANES), jnp.float32),
            pltpu.MemorySpace.VMEM((_STAT_LANES, bk), jnp.float32),
        ]
    dq, dk, dv = pl.pallas_call(
        kernel,
        in_specs=in_specs,
        out_specs=[
            pl.BlockSpec(memory_space=hbm),
            pl.BlockSpec(memory_space=hbm),
            pl.BlockSpec(memory_space=hbm),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((B * H, Tl, D), jnp.float32),
            jax.ShapeDtypeStruct((B * Hkv, Tl, D), jnp.float32),
            jax.ShapeDtypeStruct((B * Hkv, Tl, D), jnp.float32),
        ],
        scratch_shapes=[
            hbm((2, B * Hkv, Tl, D), k.dtype),     # ring KV slots
            hbm((2, B * Hkv, Tl, D), v.dtype),
            hbm((2, B * Hkv, Tl, D), jnp.float32),  # riding dk/dv accumulators
            hbm((2, B * Hkv, Tl, D), jnp.float32),
            pltpu.MemorySpace.VMEM((bq, D), q.dtype),      # tiles
            pltpu.MemorySpace.VMEM((bk, D), k.dtype),
            pltpu.MemorySpace.VMEM((bk, D), v.dtype),
            pltpu.MemorySpace.VMEM((bq, D), do.dtype),
            pltpu.MemorySpace.VMEM((bq, _STAT_LANES), jnp.float32),
            pltpu.MemorySpace.VMEM((bq, _STAT_LANES), jnp.float32),
            pltpu.MemorySpace.VMEM((bq, D), jnp.float32),
            pltpu.MemorySpace.VMEM((slab, D), jnp.float32),  # slab dk acc
            pltpu.MemorySpace.VMEM((slab, D), jnp.float32),  # slab dv acc
            *seg_tiles,
            pltpu.SemaphoreType.DMA((1,)),
            pltpu.SemaphoreType.DMA((2, 4)),
            pltpu.SemaphoreType.DMA((2, 4)),
            pltpu.SemaphoreType.REGULAR((2,)),
            pltpu.SemaphoreType.DMA((2,)),
            pltpu.SemaphoreType.DMA((2,)),
        ],
        compiler_params=tpu_compiler_params(collective_id=RING_ATTENTION_BWD_COLLECTIVE_ID),
        interpret=interpret if interpret is not None else default_interpret(),
    )(*operands)
    return (
        dq.reshape(B, H, Tl, D).astype(q.dtype),
        dk.reshape(B, Hkv, Tl, D).astype(k.dtype),
        dv.reshape(B, Hkv, Tl, D).astype(v.dtype),
    )


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6))
def ring_attention_pallas(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    axis_name: str = "context",
    causal: bool = True,
    interpret: Any = None,
    window: int = 0,
) -> jax.Array:
    """Ring attention with the KV rotation as in-kernel remote DMA.

    Must run inside ``shard_map`` with the sequence dim sharded over
    ``axis_name``; per-shard shapes q [B, H, T_local, D], k/v
    [B, Hkv, T_local, D] with H % Hkv == 0 (GQA stays at Hkv width on the
    wire). ``interpret`` accepts ``pltpu.InterpretParams`` for the
    emulated-RDMA CPU path; None defers to ``TONY_PALLAS_INTERPRET``.
    ``window`` > 0 adds the sliding-window band: below-band KV tiles (and
    whole shards) are skipped — no DMA, no grid steps — in fwd AND bwd.

    Block sizes adapt to the per-device sequence (largest ≤256 divisor
    that's a lane multiple), so short shards no longer hard-error.

    Trainable end-to-end in-kernel: the backward is its own remote-DMA ring
    kernel (``_ring_bwd_kernel``) — dk/dv accumulators ride the ring WITH
    their KV shard and a final rotation returns them home. Packed batches
    use ``ring_attention_pallas_seg``.
    """
    return _ring_fwd(q, k, v, axis_name, causal, interpret, window)[0]


def _ring_vjp_fwd(q, k, v, axis_name, causal, interpret, window):
    o, lse = _ring_fwd(q, k, v, axis_name, causal, interpret, window)
    return o, (q, k, v, o, lse)


def _ring_vjp_bwd(axis_name, causal, interpret, window, res, g):
    q, k, v, o, lse = res
    return _ring_bwd(q, k, v, o, lse, g, axis_name, causal, interpret, window)


ring_attention_pallas.defvjp(_ring_vjp_fwd, _ring_vjp_bwd)


@functools.partial(jax.custom_vjp, nondiff_argnums=(4, 5, 6, 7))
def ring_attention_pallas_seg(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    segment_ids: jax.Array,
    axis_name: str = "context",
    causal: bool = True,
    interpret: Any = None,
    window: int = 0,
) -> jax.Array:
    """Packed-sequence ring attention: ``segment_ids`` is the PER-DEVICE
    [B, T_local] slice of the packed layout (data.pack_sequences ids are
    global per row, so shard-local slices stay globally consistent); the
    kernel all-gathers the tiny id table over the ring axis and confines
    attention within segments on every shard's tiles. Composes with
    ``window`` and GQA; seg cotangent is float0.
    """
    return _ring_fwd(q, k, v, axis_name, causal, interpret, window, segment_ids)[0]


def _ring_seg_vjp_fwd(q, k, v, seg, axis_name, causal, interpret, window):
    o, lse = _ring_fwd(q, k, v, axis_name, causal, interpret, window, seg)
    return o, (q, k, v, seg, o, lse)


def _ring_seg_vjp_bwd(axis_name, causal, interpret, window, res, g):
    import numpy as np

    q, k, v, seg, o, lse = res
    dq, dk, dv = _ring_bwd(
        q, k, v, o, lse, g, axis_name, causal, interpret, window, seg
    )
    return dq, dk, dv, np.zeros(seg.shape, jax.dtypes.float0)


ring_attention_pallas_seg.defvjp(_ring_seg_vjp_fwd, _ring_seg_vjp_bwd)
