"""Ragged per-slot decode attention (Pallas TPU) for the serving engine.

The XLA decode path reads a GLOBAL length bucket of every slot's KV cache:
one long-lived request drags every slot's per-token read back to the
longest bucket (VERDICT r2 weak #3 — serving is KV-bandwidth-bound at long
context). This kernel reads each slot's cache RAGGED: slot s streams only
``ceil(lengths[s]/chunk)`` chunks from HBM through a double-buffered VMEM
pipeline, so the step's KV traffic is Σ_s len_s instead of S·max(len).
``lengths`` counts CACHE positions only — the current token's K/V arrive
via ``cur_k``/``cur_v`` and fold in as a final online-softmax step (the
r3-cont read-only-cache contract). Sliding-window models read cache from
``max(0, len + 1 - window)`` — window-sized reads, closing the r2 gap
where windowed models still read the full bucket.

Grid is (S,): one instance per slot streams [Hkv, chunk, Dh] K/V SLABS
(all kv heads per DMA — 8× bigger transfers than a per-head grid, which
measured ~2× slower end-to-end at short lengths from per-instance + DMA
overhead) and computes all heads with Hkv-batched dots, flash-style online
softmax in f32. GQA is native: q arrives grouped [Hkv, n_rep, Dh]. The
cache stays in HBM (``memory_space=ANY``); lengths arrive via scalar
prefetch so chunk counts are per-slot dynamic loop bounds, not padding.

No reference counterpart (the reference does not serve); the engine-level
contract is tested against the XLA masked-attention decode path, and the
engine picks ragged-vs-bucketed by live length (serving.ContinuousBatcher).
"""

from __future__ import annotations

import functools
import os

import jax
import jax.numpy as jnp

from tony_tpu.compat import tpu_compiler_params

_INTERPRET = os.environ.get("TONY_PALLAS_INTERPRET", "") == "1"

# cache positions streamed per DMA slab; 256 measured best on v5e (r3-cont
# ladder at 8×2048-cache slots: 128→533, 256→554, 512→531 tok/s) — bigger
# slabs amortize per-DMA overhead until VMEM pressure bites. Env-tunable;
# shrunk by halving to divide the cache length.
CHUNK = int(os.environ.get("TONY_DECODE_CHUNK", "256"))
if CHUNK < 8:  # fail at import, not inside a jit trace
    raise ValueError(f"TONY_DECODE_CHUNK={CHUNK}: DMA slab must be >= 8 positions")


def _kernel(len_ref, q_ref, ck_ref, cv_ref, k_hbm, v_hbm, o_ref, *, chunk, window,
            n_rep, pt_ref=None, staged_refs=None, count_ref=None):
    """Shared ragged-attention body. ``pt_ref=None``: dense per-slot cache —
    slab c reads ``k_hbm[0, :, c*chunk:(c+1)*chunk]``. ``pt_ref`` set: PAGED
    cache — ``k_hbm`` is the whole [P, Hkv, page_len, Dh] page pool
    (chunk == page_len) and slab c reads physical page ``pt_ref[slot, c]``;
    the logical position math (lo/c0/c1, masking) is identical because a
    page holds exactly one slab's worth of positions."""
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    s_i = pl.program_id(0)
    length = len_ref[s_i]  # CACHE positions (current token arrives via ck/cv refs)
    # staged window (paged chunked-decode): the most recent ``count`` of the
    # ``length`` positions live in the staged VMEM block, NOT the pool —
    # the pool read stops short of them and they fold in explicitly after
    count = count_ref[s_i] if count_ref is not None else jnp.int32(0)
    # clamp at 0: idle slots (length 0) carry staged garbage the caller
    # discards; a negative pool span must not start a negative-offset DMA
    pool_len = jnp.maximum(length - count, 0)
    # the current token sits at position `length`; cache band is
    # (length - window, length) — the self term is always in-window
    lo = jnp.maximum(length + 1 - window, 0) if window > 0 else jnp.int32(0)
    c0 = jnp.minimum(lo, pool_len) // chunk
    c1 = pl.cdiv(pool_len, chunk)
    Dh = q_ref.shape[-1]
    Hkv = q_ref.shape[1]
    scale = Dh ** -0.5

    def body(k_buf, v_buf, sem):
        q = q_ref[0].astype(jnp.float32) * scale  # [Hkv, n_rep, Dh]

        def dma(slot, c):
            # one DMA per buffer: the whole [Hkv, chunk, Dh] slab
            if pt_ref is None:
                k_src = k_hbm.at[0, :, pl.ds(c * chunk, chunk)]
                v_src = v_hbm.at[0, :, pl.ds(c * chunk, chunk)]
            else:
                page = pt_ref[s_i, c]
                k_src = k_hbm.at[page]
                v_src = v_hbm.at[page]
            return (
                pltpu.make_async_copy(k_src, k_buf.at[slot], sem.at[slot, 0]),
                pltpu.make_async_copy(v_src, v_buf.at[slot], sem.at[slot, 1]),
            )

        @pl.when(c0 < c1)  # a zero-length slot must not leave a DMA in flight
        def _warmup():
            for d in dma(0, c0):
                d.start()

        def step(c, carry):
            m, l, acc = carry
            i = c - c0
            cur, nxt = i % 2, (i + 1) % 2

            @pl.when(c + 1 < c1)
            def _():
                for d in dma(nxt, c + 1):
                    d.start()

            for d in dma(cur, c):
                d.wait()

            k = k_buf[cur].astype(jnp.float32)            # [Hkv, chunk, Dh]
            v = v_buf[cur].astype(jnp.float32)
            # batched over kv heads: s [Hkv, n_rep, chunk]
            s = jax.lax.dot_general(
                q, k, (((2,), (2,)), ((0,), (0,))), preferred_element_type=jnp.float32
            )
            pos = c * chunk + jax.lax.broadcasted_iota(jnp.int32, s.shape, 2)
            valid = jnp.logical_and(pos >= lo, pos < pool_len)
            s = jnp.where(valid, s, -1e30)
            m_new = jnp.maximum(m, s.max(axis=2, keepdims=True))
            p = jnp.exp(s - m_new)
            alpha = jnp.exp(m - m_new)
            l = l * alpha + p.sum(axis=2, keepdims=True)
            pv = jax.lax.dot_general(                      # [Hkv, n_rep, Dh]
                p, v, (((2,), (1,)), ((0,), (0,))), preferred_element_type=jnp.float32
            )
            acc = acc * alpha + pv
            return m_new, l, acc

        m0 = jnp.full((Hkv, n_rep, 1), -1e30, jnp.float32)
        l0 = jnp.zeros((Hkv, n_rep, 1), jnp.float32)
        acc0 = jnp.zeros((Hkv, n_rep, Dh), jnp.float32)
        m, l, acc = jax.lax.fori_loop(c0, c1, step, (m0, l0, acc0))

        def fold_one(kv, pos_valid, carry):
            """One explicit (k, v) pair as an online-softmax step."""
            m, l, acc = carry
            k1, v1 = kv
            s1 = jax.lax.dot_general(   # [Hkv, n_rep] (q pre-scaled)
                q, k1, (((2,), (1,)), ((0,), (0,))),
                preferred_element_type=jnp.float32,
            )[..., None]
            s1 = jnp.where(pos_valid, s1, -1e30)
            m_new = jnp.maximum(m, s1)
            alpha = jnp.exp(m - m_new)
            p1 = jnp.exp(s1 - m_new)
            return m_new, l * alpha + p1, acc * alpha + p1 * v1[:, None, :]

        if staged_refs is not None:
            # staged window: positions pool_len .. length-1 (this chunk's
            # earlier tokens, not yet flushed to the pool), VMEM-resident.
            # Dynamic trip count: step i has only i live entries — looping
            # the full static window would double the serial fold chain
            sk_ref, sv_ref = staged_refs

            def staged_step(j, carry):
                p = pool_len + j
                return fold_one(
                    (sk_ref[0, j].astype(jnp.float32),
                     sv_ref[0, j].astype(jnp.float32)),
                    p >= lo, carry,
                )

            m, l, acc = jax.lax.fori_loop(0, count, staged_step, (m, l, acc))

        # fold the current token (position `length`) as a final online step:
        # the cache stays read-only and a zero-length slot still normalizes
        m, l, acc = fold_one(
            (ck_ref[0].astype(jnp.float32), cv_ref[0].astype(jnp.float32)),
            jnp.bool_(True), (m, l, acc),
        )
        o_ref[0] = (acc / jnp.maximum(l, 1e-30)).astype(o_ref.dtype)

    pl.run_scoped(
        body,
        k_buf=pltpu.VMEM((2, Hkv, chunk, Dh), k_hbm.dtype),
        v_buf=pltpu.VMEM((2, Hkv, chunk, Dh), v_hbm.dtype),
        sem=pltpu.SemaphoreType.DMA((2, 2)),
    )


@functools.partial(jax.jit, static_argnames=("window", "chunk"))
def ragged_decode_attention(
    q: jax.Array,        # [S, H, Dh] — one new token per slot
    ck: jax.Array,       # [S, Hkv, maxT, Dh] — read-only cache
    cv: jax.Array,
    lengths: jax.Array,  # [S] int32 — CACHE positions (excluding current token)
    *,
    cur_k: jax.Array,    # [S, Hkv, Dh] — current token's K (not yet cached)
    cur_v: jax.Array,
    window: int = 0,
    chunk: int = CHUNK,
) -> jax.Array:
    """Per-slot ragged cache attention; returns o [S, H, Dh].

    Slot s attends cache positions [max(0, len_s + 1 - window), len_s) plus
    the current token (its K/V arrive via ``cur_k``/``cur_v``, folded as a
    final online-softmax step) — the cache is never written here, so the
    engine can defer the cache write to one small scatter per step.
    HBM traffic per step is Σ_s ceil(len_s/chunk)·chunk positions.

    PRECONDITION: ``lengths[s] < maxT`` for every slot whose output is
    consumed. At ``lengths == maxT`` (only reachable via the engine's
    clamped write position for retired-not-yet-flushed slots) position
    maxT-1 is attended twice — once as stale cache, once as the current
    token — and the result is garbage the caller must discard.
    """
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    S, H, Dh = q.shape
    Hkv, maxT = ck.shape[1], ck.shape[2]
    n_rep = H // Hkv
    chunk = min(chunk, maxT)
    while chunk > 8 and maxT % chunk:  # shrink to divide (cf. _block_sizes)
        chunk //= 2
    if maxT % chunk:  # floor at 8: a 1-position slab would be a perf cliff
        raise ValueError(f"cache max_len {maxT} has no slab size >= 8 that divides it")
    qg = q.reshape(S, Hkv, n_rep, Dh)

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1,
        grid=(S,),
        in_specs=[
            pl.BlockSpec((1, Hkv, n_rep, Dh), lambda s, L: (s, 0, 0, 0)),
            pl.BlockSpec((1, Hkv, Dh), lambda s, L: (s, 0, 0)),
            pl.BlockSpec((1, Hkv, Dh), lambda s, L: (s, 0, 0)),
            pl.BlockSpec(memory_space=pl.ANY),   # ck stays in HBM
            pl.BlockSpec(memory_space=pl.ANY),   # cv stays in HBM
        ],
        out_specs=pl.BlockSpec((1, Hkv, n_rep, Dh), lambda s, L: (s, 0, 0, 0)),
    )

    def kern(len_ref, q_ref, ck_ref, cv_ref, k_hbm, v_hbm, o_ref):
        s_i = pl.program_id(0)
        _kernel(
            len_ref, q_ref, ck_ref, cv_ref,
            k_hbm.at[pl.ds(s_i, 1)],
            v_hbm.at[pl.ds(s_i, 1)],
            o_ref, chunk=chunk, window=window, n_rep=n_rep,
        )

    o = pl.pallas_call(
        kern,
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((S, Hkv, n_rep, Dh), q.dtype),
        compiler_params=tpu_compiler_params(
            dimension_semantics=("arbitrary",),
        ),
        interpret=_INTERPRET,
        cost_estimate=pl.CostEstimate(
            flops=4 * S * H * maxT * Dh,
            bytes_accessed=(ck.size + cv.size) * ck.dtype.itemsize // 4,
            transcendentals=S * H * maxT,
        ),
    )(lengths, qg, cur_k, cur_v, ck, cv)
    return o.reshape(S, H, Dh)


@functools.partial(jax.jit, static_argnames=("window",))
def paged_decode_attention(
    q: jax.Array,           # [S, H, Dh] — one new token per slot
    kp: jax.Array,          # [P, Hkv, page_len, Dh] — page pool (read-only)
    vp: jax.Array,
    lengths: jax.Array,     # [S] int32 — CACHE positions (excluding current)
    page_table: jax.Array,  # [S, max_pages] int32 — logical page j → physical
    *,
    cur_k: jax.Array,       # [S, Hkv, Dh]
    cur_v: jax.Array,
    window: int = 0,
    staged_k: jax.Array | None = None,  # [S, W, Hkv, Dh] — chunk staging
    staged_v: jax.Array | None = None,
    staged_count: jax.Array | None = None,  # [S] int32 — live staged entries
) -> jax.Array:
    """Ragged decode attention over a PAGED cache; returns o [S, H, Dh].

    Identical math and streaming structure to ``ragged_decode_attention``
    (one grid instance per slot, double-buffered slab DMA, online softmax,
    current token folded as the final step) with one indirection: the DMA
    slab size is the PAGE size, and slab c of slot s reads physical page
    ``page_table[s, c]`` of the pool. HBM traffic per step is still
    Σ_s ceil(len_s/page_len)·page_len positions — the pool's total size P
    is irrelevant to step cost, which is the whole point: HBM footprint
    tracks allocated pages, not slots × max_len. Entries of ``page_table``
    beyond slot s's live pages are never read (loop bounds come from
    ``lengths``); SWA slots skip whole pages below the window exactly as
    the dense kernel skips slabs.

    CHUNKED DECODE STAGING: with ``staged_k/v/count``, the most recent
    ``staged_count[s]`` of the ``lengths[s]`` positions live in the staged
    buffer (this decode chunk's not-yet-flushed columns), NOT the pool —
    the pool read stops short of them and they fold in as explicit
    online-softmax steps from VMEM. This is what lets the engine write the
    pool ONCE per chunk instead of once per token (the per-token scatter
    measured −24%/chunk on v5e).

    Same PRECONDITION as the dense kernel: consumed slots have
    ``lengths[s] < max_pages * page_len`` and their pages allocated.
    """
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    S, H, Dh = q.shape
    Hkv, page_len = kp.shape[1], kp.shape[2]
    n_rep = H // Hkv
    if page_len < 8 or page_len % 8:
        raise ValueError(
            f"page_len {page_len} must be a multiple of 8 (>= 8): the "
            "slab-DMA/sublane layout assumes sublane-aligned pages"
        )
    qg = q.reshape(S, Hkv, n_rep, Dh)
    has_staged = staged_k is not None
    if has_staged and (staged_v is None or staged_count is None):
        raise ValueError("staged_k needs staged_v and staged_count")
    # two scalar-prefetch operands (lengths+counts, page_table). A packed
    # single-operand variant was built and A/B'd on-chip: 342 vs 341
    # ms/chunk — neutral, so the simpler form ships.
    meta = (
        jnp.stack([lengths, staged_count], axis=1).astype(jnp.int32)
        if has_staged else lengths[:, None]
    )

    staged_specs = (
        [
            pl.BlockSpec((1,) + staged_k.shape[1:], lambda s, M, PT: (s, 0, 0, 0)),
            pl.BlockSpec((1,) + staged_k.shape[1:], lambda s, M, PT: (s, 0, 0, 0)),
        ]
        if has_staged else []
    )
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,  # meta [S, 1|2], page_table
        grid=(S,),
        in_specs=[
            pl.BlockSpec((1, Hkv, n_rep, Dh), lambda s, M, PT: (s, 0, 0, 0)),
            pl.BlockSpec((1, Hkv, Dh), lambda s, M, PT: (s, 0, 0)),
            pl.BlockSpec((1, Hkv, Dh), lambda s, M, PT: (s, 0, 0)),
            *staged_specs,
            pl.BlockSpec(memory_space=pl.ANY),   # kp stays in HBM
            pl.BlockSpec(memory_space=pl.ANY),   # vp stays in HBM
        ],
        out_specs=pl.BlockSpec((1, Hkv, n_rep, Dh), lambda s, M, PT: (s, 0, 0, 0)),
    )

    class _Col:
        """A 1-column view over the packed meta operand."""

        def __init__(self, ref, col):
            self.ref, self.col = ref, col

        def __getitem__(self, s):
            return self.ref[s, self.col]

    def kern(meta_ref, pt_ref, q_ref, ck_ref, cv_ref, *rest):
        if has_staged:
            sk_ref, sv_ref, k_hbm, v_hbm, o_ref = rest
            staged_refs = (sk_ref, sv_ref)
            count_ref = _Col(meta_ref, 1)
        else:
            k_hbm, v_hbm, o_ref = rest
            staged_refs = count_ref = None
        _kernel(
            _Col(meta_ref, 0), q_ref, ck_ref, cv_ref, k_hbm, v_hbm, o_ref,
            chunk=page_len, window=window, n_rep=n_rep, pt_ref=pt_ref,
            staged_refs=staged_refs, count_ref=count_ref,
        )

    operands = [meta, page_table, qg, cur_k, cur_v]
    if has_staged:
        operands += [staged_k, staged_v]
    operands += [kp, vp]
    o = pl.pallas_call(
        kern,
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((S, Hkv, n_rep, Dh), q.dtype),
        compiler_params=tpu_compiler_params(
            dimension_semantics=("arbitrary",),
        ),
        interpret=_INTERPRET,
        cost_estimate=pl.CostEstimate(
            flops=4 * S * H * page_table.shape[1] * page_len * Dh,
            bytes_accessed=(kp.size + vp.size) * kp.dtype.itemsize // 4,
            transcendentals=S * H * page_table.shape[1] * page_len,
        ),
    )(*operands)
    return o.reshape(S, H, Dh)
