"""Compute ops: Pallas TPU kernels + XLA references (the hot path)."""

from tony_tpu.ops.attention import attention_reference, flash_attention, mha, repeat_kv  # noqa: F401
from tony_tpu.ops.ring import ring_attention_pallas  # noqa: F401
from tony_tpu.ops.quant import QTensor, dequantize, int8_matmul, quantize_int8, quantize_tree  # noqa: F401
from tony_tpu.ops.layers import (  # noqa: F401
    apply_rope,
    chunked_cross_entropy_loss,
    cross_entropy_loss,
    gelu_mlp,
    layer_norm,
    rms_norm,
    rope_frequencies,
    swiglu,
)
