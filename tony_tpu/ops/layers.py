"""Elementwise/normalization building blocks (XLA-fused on TPU).

These stay as plain jnp expressions on purpose: XLA fuses RMSNorm/RoPE/SwiGLU
into adjacent matmuls (the HBM-bandwidth win hand-written kernels would chase)
— Pallas is reserved for ops XLA can't schedule well (attention, ring
collectives, quantization; see ops/attention.py, ops/quant.py).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp


def rms_norm(x: jax.Array, weight: jax.Array, eps: float = 1e-6) -> jax.Array:
    """RMSNorm in f32 accumulation regardless of input dtype."""
    xf = x.astype(jnp.float32)
    var = jnp.mean(xf * xf, axis=-1, keepdims=True)
    return (xf * jax.lax.rsqrt(var + eps)).astype(x.dtype) * weight


def layer_norm(x: jax.Array, weight: jax.Array, bias: jax.Array, eps: float = 1e-6) -> jax.Array:
    xf = x.astype(jnp.float32)
    mean = jnp.mean(xf, axis=-1, keepdims=True)
    var = jnp.var(xf, axis=-1, keepdims=True)
    return ((xf - mean) * jax.lax.rsqrt(var + eps)).astype(x.dtype) * weight + bias


def rope_frequencies(
    dim: int, max_seq: int, theta: float = 10000.0, scaling: tuple = ()
) -> tuple[jax.Array, jax.Array]:
    """cos/sin tables [max_seq, dim//2] in f32.

    ``scaling`` (hashable tuple so configs stay frozen/static):
      ()                                → no scaling,
      ("linear", factor)                → positions divided by factor,
      ("llama3", factor, low_freq_factor, high_freq_factor, original_max)
        → Llama-3.1 frequency-band scaling (matches the HF implementation:
        low-frequency bands divided by factor, high-frequency bands kept,
        the middle band smoothly interpolated).
    """
    inv_freq = 1.0 / (theta ** (jnp.arange(0, dim, 2, dtype=jnp.float32) / dim))
    t = jnp.arange(max_seq, dtype=jnp.float32)
    if scaling:
        kind = scaling[0]
        if kind == "linear":
            t = t / float(scaling[1])
        elif kind == "llama3":
            factor, lo, hi, orig = (float(s) for s in scaling[1:])
            wavelen = 2.0 * jnp.pi / inv_freq
            smooth = (orig / wavelen - lo) / (hi - lo)
            scaled = jnp.where(
                wavelen > orig / lo,                       # low-frequency band
                inv_freq / factor,
                jnp.where(
                    wavelen < orig / hi,                   # high-frequency band
                    inv_freq,
                    (1.0 - smooth) * inv_freq / factor + smooth * inv_freq,
                ),
            )
            inv_freq = scaled
        else:
            raise ValueError(f"unknown rope scaling kind {kind!r} (linear|llama3)")
    freqs = jnp.outer(t, inv_freq)
    return jnp.cos(freqs), jnp.sin(freqs)


def apply_rope(
    x: jax.Array, cos: jax.Array, sin: jax.Array, positions: jax.Array | None = None
) -> jax.Array:
    """Rotary embedding; x: [B, H, T, D], tables [>=T, D//2].

    ``positions``: [T] shared positions, or [B, T] per-batch positions
    (packed sequences restart positions at each segment)."""
    T = x.shape[-2]
    if positions is None:
        c, s = cos[:T], sin[:T]
    else:
        c, s = cos[positions], sin[positions]
        if positions.ndim == 2:  # [B, T, D/2] → broadcast over heads
            c, s = c[:, None], s[:, None]
    x1, x2 = jnp.split(x.astype(jnp.float32), 2, axis=-1)
    out = jnp.concatenate([x1 * c - x2 * s, x2 * c + x1 * s], axis=-1)
    return out.astype(x.dtype)


def swiglu(x: jax.Array, w_gate: jax.Array, w_up: jax.Array, w_down: jax.Array) -> jax.Array:
    """SwiGLU MLP: (silu(x@Wg) * (x@Wu)) @ Wd, bf16-friendly."""
    g = jax.nn.silu(jnp.einsum("...d,df->...f", x, w_gate))
    u = jnp.einsum("...d,df->...f", x, w_up)
    return jnp.einsum("...f,fd->...d", g * u, w_down)


def gelu_mlp(x: jax.Array, w_in: jax.Array, b_in: jax.Array, w_out: jax.Array, b_out: jax.Array) -> jax.Array:
    h = jax.nn.gelu(jnp.einsum("...d,df->...f", x, w_in) + b_in)
    return jnp.einsum("...f,fd->...d", h, w_out) + b_out


def cross_entropy_loss(
    logits: jax.Array, targets: jax.Array, ignore_index: int = -100
) -> tuple[jax.Array, jax.Array]:
    """Token-mean CE in f32; returns (loss, n_valid_tokens)."""
    mask = targets != ignore_index
    safe_targets = jnp.where(mask, targets, 0)
    logits = logits.astype(jnp.float32)
    logz = jax.nn.logsumexp(logits, axis=-1)
    gold = jnp.take_along_axis(logits, safe_targets[..., None], axis=-1)[..., 0]
    nll = (logz - gold) * mask
    n = jnp.maximum(mask.sum(), 1)
    return nll.sum() / n, n


def chunked_cross_entropy_loss(
    x: jax.Array,
    lm_head: jax.Array,
    targets: jax.Array,
    ignore_index: int = -100,
    chunk: int = 512,
) -> tuple[jax.Array, jax.Array]:
    """Fused lm-head + CE that never materializes the [B, T, V] logits.

    A ``lax.scan`` over sequence chunks computes each chunk's logits, its
    logsumexp, and the gold logit, keeping only O(B·chunk·V) live; the
    chunk body is checkpointed so the backward recomputes per-chunk logits
    instead of saving them. At Llama-scale vocab this removes the largest
    activation in the train step (the bf16 logits + f32 softmax temps),
    which is what bounds the per-chip batch size.

    x: [B, T, D] final hidden states; lm_head: [D, V]; targets: [B, T].
    """
    B, T, D = x.shape
    chunk = T if chunk <= 0 else min(chunk, T)
    pad = (-T) % chunk
    if pad:
        # pad to a chunk multiple with ignored targets: keeps the memory
        # bound AND the chunk-sized matmuls for awkward sequence lengths
        # (a divisor-based fallback would degenerate to tiny chunks)
        x = jnp.pad(x, ((0, 0), (0, pad), (0, 0)))
        targets = jnp.pad(targets, ((0, 0), (0, pad)), constant_values=ignore_index)
        T += pad
    n_chunks = T // chunk
    mask_all = targets != ignore_index
    xs = x.reshape(B, n_chunks, chunk, D).transpose(1, 0, 2, 3)
    ts = targets.reshape(B, n_chunks, chunk).transpose(1, 0, 2)

    def chunk_nll(carry, xt):
        xc, tc = xt
        logits = jnp.einsum(
            "bcd,dv->bcv", xc, lm_head, preferred_element_type=jnp.float32
        )
        mask = tc != ignore_index
        safe = jnp.where(mask, tc, 0)
        logz = jax.nn.logsumexp(logits, axis=-1)
        # gold logit via masked reduce (fuses; no gather, so vocab-parallel
        # TP shards reduce locally and psum instead of rematerializing)
        iota = jax.lax.broadcasted_iota(jnp.int32, logits.shape, 2)
        gold = jnp.sum(jnp.where(iota == safe[..., None], logits, 0.0), axis=-1)
        return carry + jnp.sum((logz - gold) * mask), None

    total, _ = jax.lax.scan(jax.checkpoint(chunk_nll), jnp.float32(0.0), (xs, ts))
    n = jnp.maximum(mask_all.sum(), 1)
    return total / n, n
