"""Weight-only int8 quantization: absmax per-channel + fused dequant matmul.

New capability over the reference (its compute lived in user frameworks —
SURVEY.md §2.4). The serving-side win on TPU is HBM bandwidth: int8 weights
halve the bytes streamed per matmul versus bf16, and the Pallas kernel
fuses the dequant into the MXU epilogue so no bf16 copy of the weight ever
exists in HBM. Training stays bf16; quantize at export time.
"""

from __future__ import annotations

import functools
import os
from typing import NamedTuple

import jax
import jax.numpy as jnp

from tony_tpu.compat import tpu_compiler_params

_INTERPRET = os.environ.get("TONY_PALLAS_INTERPRET", "") == "1"


class QTensor(NamedTuple):
    """Per-output-channel absmax int8 quantization of a [..., K, N] weight."""

    q: jax.Array      # int8 [..., K, N]
    scale: jax.Array  # f32  [..., N] (absmax over the K/contraction dim)


def quantize_int8(w: jax.Array) -> QTensor:
    """[..., K, N] float → QTensor with per-N-channel absmax scales.

    Leading dims (e.g. the stacked-layer dim) quantize independently."""
    wf = w.astype(jnp.float32)
    scale = jnp.max(jnp.abs(wf), axis=-2) / 127.0
    scale = jnp.maximum(scale, 1e-8)
    q = jnp.clip(jnp.round(wf / scale[..., None, :]), -127, 127).astype(jnp.int8)
    return QTensor(q, scale)


def dequantize(qt: QTensor, dtype=jnp.bfloat16) -> jax.Array:
    return (qt.q.astype(jnp.float32) * qt.scale[..., None, :]).astype(dtype)


def int8_matmul_ref(x: jax.Array, qt: QTensor) -> jax.Array:
    """XLA reference: x [.., K] @ dequant [K, N] → [.., N] in x.dtype."""
    out = jnp.einsum(
        "...k,kn->...n", x.astype(jnp.float32), qt.q.astype(jnp.float32)
    )
    return (out * qt.scale).astype(x.dtype)


def _quant_matmul_kernel(x_ref, q_ref, s_ref, o_ref, acc_ref, *, n_k: int):
    """Grid (M//bm, N//bn, K//bk), K innermost. int8 block is cast to bf16 in
    VMEM (HBM streamed at 1 byte/weight), dot accumulates f32 in scratch, and
    the per-channel scale lands in the epilogue of the last K step."""
    from jax.experimental import pallas as pl

    k_idx = pl.program_id(2)

    @pl.when(k_idx == 0)
    def _init():
        acc_ref[:] = jnp.zeros_like(acc_ref)

    x = x_ref[:].astype(jnp.bfloat16)
    w = q_ref[:].astype(jnp.bfloat16)
    acc_ref[:] += jax.lax.dot_general(
        x, w, (((1,), (0,)), ((), ())), preferred_element_type=jnp.float32
    )

    @pl.when(k_idx == n_k - 1)
    def _epilogue():
        o_ref[:] = (acc_ref[:] * s_ref[:][0]).astype(o_ref.dtype)


@functools.partial(jax.jit, static_argnames=("block_m", "block_n", "block_k"))
def int8_matmul(
    x: jax.Array,
    qt: QTensor,
    *,
    block_m: int | None = None,
    block_n: int | None = None,
    block_k: int | None = None,
) -> jax.Array:
    """Fused dequant matmul: x [M, K] (or [..., K]) @ QTensor[K, N] → [..., N].

    Falls back to the XLA reference when shapes don't tile evenly. Blocks
    left unset resolve to an ops/tune.py cache hit for this (M, K, N) on
    this device, else the measured defaults (256, 256, 512) — resolution is
    trace-time (the blocks are static kernel parameters).
    """
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    lead = x.shape[:-1]
    K = x.shape[-1]
    N = qt.q.shape[1]
    xm = x.reshape(-1, K)
    M = xm.shape[0]
    if block_m is None or block_n is None or block_k is None:
        from tony_tpu.ops import tune

        tuned = tune.lookup("int8_matmul", (M, K, N), str(x.dtype)) or {}

        def _pick(given, key, default, align):
            # explicit caller blocks pass through; TUNED values must satisfy
            # the kernel's alignment preconditions or they degrade to the
            # shipped default (a corrupt cache entry — 0, negative, odd —
            # must never turn into a trace-time ZeroDivisionError)
            if given is not None:
                return given
            t = int(tuned.get(key, 0) or 0)
            return t if t >= align and t % align == 0 else default

        block_m = _pick(block_m, "block_m", 256, 8)
        block_n = _pick(block_n, "block_n", 256, 128)
        block_k = _pick(block_k, "block_k", 512, 128)
    bm, bn, bk = min(block_m, M), min(block_n, N), min(block_k, K)
    # TPU minimum-tile alignment (8 sublanes × 128 lanes for f32 blocks) in
    # addition to even tiling — sub-tile blocks would fail Mosaic lowering
    # on hardware even though the interpreter accepts them (batch-1 decode,
    # tiny K, etc. route to XLA, which handles small shapes fine).
    # decode-sized row counts underfill the kernel's M tile: the XLA
    # reference (dequant fused into the einsum) measured faster at M ≤ 32 on
    # BOTH bench geometries (8B-geometry chunk 233→181 ms, 1B 181→171 ms —
    # r3-cont); the kernel is the prefill/training-sized path
    if M < 64 or (M % bm or N % bn or K % bk or bm % 8 or bk % 128 or bn % 128):
        return int8_matmul_ref(x, qt)
    n_k = K // bk

    out = pl.pallas_call(
        functools.partial(_quant_matmul_kernel, n_k=n_k),
        grid=(M // bm, N // bn, n_k),
        in_specs=[
            pl.BlockSpec((bm, bk), lambda i, j, k: (i, k)),
            pl.BlockSpec((bk, bn), lambda i, j, k: (k, j)),
            pl.BlockSpec((1, bn), lambda i, j, k: (0, j)),
        ],
        out_specs=pl.BlockSpec((bm, bn), lambda i, j, k: (i, j)),
        out_shape=jax.ShapeDtypeStruct((M, N), x.dtype),
        scratch_shapes=[pltpu.VMEM((bm, bn), jnp.float32)],
        compiler_params=tpu_compiler_params(
            dimension_semantics=("parallel", "parallel", "arbitrary")
        ),
        interpret=_INTERPRET,
        cost_estimate=pl.CostEstimate(
            flops=2 * M * N * K,
            bytes_accessed=M * K * x.dtype.itemsize + K * N + M * N * x.dtype.itemsize,
            transcendentals=0,
        ),
    )(xm, qt.q, qt.scale.reshape(1, N))
    return out.reshape(*lead, N)


def quantize_tree(params, min_size: int = 1 << 16):
    """Quantize every >=2-D float leaf with >= min_size elements to QTensor
    (weight-only int8 export; stacked-layer leading dims quantize per layer);
    small/1-D leaves (norms, biases) stay float.

    Returns (tree-with-QTensor-leaves, bytes_before, bytes_after)."""
    before = after = 0
    _SKIP_SUFFIXES = ("norm", "bias", "scale", "ln")

    def visit(path, leaf):
        nonlocal before, after
        sz = leaf.size * leaf.dtype.itemsize
        before += sz
        # two guards against quantizing non-matmul weights:
        # 1. name-based: ANY path segment ending in norm/bias/scale/ln marks
        #    a norm/bias (stacks are [L, D] — 2-D and large at real model
        #    scale, but quantizing them breaks the layer scan and is
        #    numerically wrong; nested layouts like attn_norm/{w,b} put the
        #    telling name on an inner segment). Suffix-of-segment, not
        #    substring, so projections like "upscale_proj" still quantize.
        # 2. shape-based: both trailing dims must look like matmul [K, N].
        segments = [str(getattr(k, "key", k)).lower() for k in path]
        named_skip = any(seg.endswith(s) for seg in segments for s in _SKIP_SUFFIXES)
        is_matmul_like = (
            leaf.ndim >= 2 and leaf.shape[-1] >= 64 and leaf.shape[-2] >= 64
        )
        if (
            not named_skip
            and is_matmul_like
            and leaf.size >= min_size
            and jnp.issubdtype(leaf.dtype, jnp.floating)
        ):
            qt = quantize_int8(leaf)
            after += qt.q.size + qt.scale.size * 4
            return qt
        after += sz
        return leaf

    tree = jax.tree_util.tree_map_with_path(visit, params)
    return tree, before, after
