"""Attention kernels: XLA reference + Pallas TPU flash attention.

The compute hot path the reference never owned (it lived inside TF/torch —
SURVEY.md §2.4): here multi-head attention is a first-class op with
- ``attention_reference``: einsum+softmax through XLA (runs everywhere; XLA
  already fuses mask+softmax into the matmuls well on TPU),
- ``flash_attention``: blockwise-online-softmax Pallas kernel keeping the
  score matrix in VMEM tiles (O(T) memory), for long sequences on TPU,
- ``mha``: the dispatcher models call (impl='auto' picks per backend).

GQA/MQA is handled by broadcasting KV heads before the kernel.
"""

from __future__ import annotations

import functools
import os

import jax
import jax.numpy as jnp

NEG_INF = -1e30

# Per-row stats (logsumexp, delta) are carried with a trailing lane dim of
# this size: TPU Pallas requires >=2-D tiles whose last dim is 128-divisible
# OR equal to the full array dim — a small full-width lane dim keeps the
# HBM cost of the stats negligible while satisfying the tiling rule.
_STAT_LANES = 8

# CPU tests run the TPU kernels through the Pallas interpreter (the reference
# tests multi-node logic without a cluster; same idea for kernels without a chip)
_INTERPRET = os.environ.get("TONY_PALLAS_INTERPRET", "") == "1"


def repeat_kv(k: jax.Array, n_rep: int) -> jax.Array:
    """[B, Hkv, T, D] → [B, Hkv*n_rep, T, D] (GQA head broadcast)."""
    if n_rep == 1:
        return k
    B, H, T, D = k.shape
    return jnp.broadcast_to(k[:, :, None], (B, H, n_rep, T, D)).reshape(B, H * n_rep, T, D)


def attention_reference(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    *,
    causal: bool = True,
    scale: float | None = None,
) -> jax.Array:
    """Plain attention; q/k/v: [B, H, T, D] (KV already head-broadcast)."""
    D = q.shape[-1]
    scale = scale if scale is not None else D ** -0.5
    s = jnp.einsum("bhqd,bhkd->bhqk", q, k, preferred_element_type=jnp.float32) * scale
    if causal:
        Tq, Tk = s.shape[-2], s.shape[-1]
        mask = jnp.tril(jnp.ones((Tq, Tk), dtype=bool), Tk - Tq)
        s = jnp.where(mask, s, NEG_INF)
    p = jax.nn.softmax(s, axis=-1)
    return jnp.einsum("bhqk,bhkd->bhqd", p.astype(v.dtype), v)


# ---------------------------------------------------------------------------
# Pallas flash attention (TPU)
# ---------------------------------------------------------------------------

def _flash_kernel(q_ref, k_ref, v_ref, o_ref, lse_ref, *, block_k: int, causal: bool, scale: float):
    """Grid: (B*H, Tq//block_q). Online softmax over KV blocks in VMEM.

    Also emits the per-row logsumexp (scaled-score space) so the Pallas
    backward can recompute probabilities blockwise without the T×T matrix.
    """
    from jax.experimental import pallas as pl

    block_q, D = q_ref.shape
    Tk = k_ref.shape[0]
    q_blk_idx = pl.program_id(1)
    q = q_ref[:] .astype(jnp.float32) * scale
    q_pos = q_blk_idx * block_q + jax.lax.broadcasted_iota(jnp.int32, (block_q, 1), 0)

    num_k_blocks = pl.cdiv(Tk, block_k)
    if causal:
        # only blocks at or below the diagonal contribute
        num_k_blocks = jnp.minimum(num_k_blocks, (q_blk_idx + 1) * block_q // block_k + 1)

    def body(kb, carry):
        o, m, l = carry
        k_blk = k_ref[pl.ds(kb * block_k, block_k), :].astype(jnp.float32)
        v_blk = v_ref[pl.ds(kb * block_k, block_k), :].astype(jnp.float32)
        s = jax.lax.dot_general(
            q, k_blk, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        )  # [block_q, block_k]
        if causal:
            k_pos = kb * block_k + jax.lax.broadcasted_iota(jnp.int32, (1, block_k), 1)
            s = jnp.where(q_pos >= k_pos, s, NEG_INF)
        m_b = jnp.max(s, axis=-1, keepdims=True)
        m_new = jnp.maximum(m, m_b)
        alpha = jnp.exp(m - m_new)
        p = jnp.exp(s - m_new)
        l_new = l * alpha + jnp.sum(p, axis=-1, keepdims=True)
        o_new = o * alpha + jax.lax.dot_general(
            p, v_blk, (((1,), (0,)), ((), ())), preferred_element_type=jnp.float32
        )
        return o_new, m_new, l_new

    o0 = jnp.zeros((block_q, D), jnp.float32)
    m0 = jnp.full((block_q, 1), NEG_INF, jnp.float32)
    l0 = jnp.zeros((block_q, 1), jnp.float32)
    o, m, l = jax.lax.fori_loop(0, num_k_blocks, body, (o0, m0, l0))
    l = jnp.maximum(l, 1e-20)
    o_ref[:] = (o / l).astype(o_ref.dtype)
    lse_ref[:] = jnp.broadcast_to(m + jnp.log(l), (block_q, _STAT_LANES))


def _flash_fwd_impl(
    q: jax.Array, k: jax.Array, v: jax.Array, causal: bool, block_q: int, block_k: int
) -> tuple[jax.Array, jax.Array]:
    """Shared forward: ([B,H,Tq,D], lse [B,H,Tq]) — shapes pre-validated."""
    out, lse_lanes = _flash_fwd_lanes(q, k, v, causal, block_q, block_k)
    return out, lse_lanes[:, :, :, 0]


def _flash_fwd_lanes(
    q: jax.Array, k: jax.Array, v: jax.Array, causal: bool, block_q: int, block_k: int
) -> tuple[jax.Array, jax.Array]:
    """Forward returning the lane-replicated lse [B,H,Tq,_STAT_LANES] so the
    backward can feed it to the Pallas kernels without a re-broadcast."""
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    B, H, Tq, D = q.shape
    Tk = k.shape[2]
    scale = D ** -0.5
    qf = q.reshape(B * H, Tq, D)
    kf = k.reshape(B * H, Tk, D)
    vf = v.reshape(B * H, Tk, D)

    kernel = functools.partial(_flash_kernel, block_k=block_k, causal=causal, scale=scale)
    out, lse = pl.pallas_call(
        kernel,
        grid=(B * H, Tq // block_q),
        in_specs=[
            pl.BlockSpec((None, block_q, D), lambda b, i: (b, i, 0)),
            pl.BlockSpec((None, Tk, D), lambda b, i: (b, 0, 0)),
            pl.BlockSpec((None, Tk, D), lambda b, i: (b, 0, 0)),
        ],
        out_specs=[
            pl.BlockSpec((None, block_q, D), lambda b, i: (b, i, 0)),
            pl.BlockSpec((None, block_q, _STAT_LANES), lambda b, i: (b, i, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((B * H, Tq, D), q.dtype),
            jax.ShapeDtypeStruct((B * H, Tq, _STAT_LANES), jnp.float32),
        ],
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("parallel", "arbitrary"),
        ),
        interpret=_INTERPRET,
        cost_estimate=pl.CostEstimate(
            flops=4 * B * H * Tq * Tk * D,
            bytes_accessed=2 * (qf.size + kf.size + vf.size) * q.dtype.itemsize,
            transcendentals=B * H * Tq * Tk,
        ),
    )(qf, kf, vf)
    return out.reshape(B, H, Tq, D), lse.reshape(B, H, Tq, _STAT_LANES)


@functools.partial(jax.jit, static_argnames=("causal", "block_q", "block_k"))
def flash_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    *,
    causal: bool = True,
    block_q: int = 256,
    block_k: int = 256,
) -> jax.Array:
    """Pallas TPU flash attention; q/k/v: [B, H, T, D], T % block == 0."""
    B, H, Tq, D = q.shape
    Tk = k.shape[2]
    block_q = min(block_q, Tq)
    block_k = min(block_k, Tk)
    if Tq % block_q or Tk % block_k:
        return attention_reference(q, k, v, causal=causal)
    return _flash_fwd_impl(q, k, v, causal, block_q, block_k)[0]


def _flash_bwd_dq_kernel(
    q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, dq_ref,
    *, block_k: int, causal: bool, scale: float,
):
    """Grid: (B*H, Tq//block_q). dq[i] = scale · Σ_kb ds[i,kb] @ k[kb]."""
    from jax.experimental import pallas as pl

    block_q, D = q_ref.shape
    Tk = k_ref.shape[0]
    q_blk_idx = pl.program_id(1)
    q = q_ref[:].astype(jnp.float32)
    do = do_ref[:].astype(jnp.float32)
    lse = lse_ref[:][:, :1]            # [block_q, 1] (lanes identical)
    delta = delta_ref[:][:, :1]        # [block_q, 1]
    q_pos = q_blk_idx * block_q + jax.lax.broadcasted_iota(jnp.int32, (block_q, 1), 0)

    num_k_blocks = pl.cdiv(Tk, block_k)
    if causal:
        num_k_blocks = jnp.minimum(num_k_blocks, (q_blk_idx + 1) * block_q // block_k + 1)

    def body(kb, dq):
        k_blk = k_ref[pl.ds(kb * block_k, block_k), :].astype(jnp.float32)
        v_blk = v_ref[pl.ds(kb * block_k, block_k), :].astype(jnp.float32)
        s = scale * jax.lax.dot_general(
            q, k_blk, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        )
        if causal:
            k_pos = kb * block_k + jax.lax.broadcasted_iota(jnp.int32, (1, block_k), 1)
            s = jnp.where(q_pos >= k_pos, s, NEG_INF)
        p = jnp.exp(s - lse)                                   # [block_q, block_k]
        dp = jax.lax.dot_general(                              # do @ v^T
            do, v_blk, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        )
        ds = p * (dp - delta)
        dq = dq + jax.lax.dot_general(                         # ds @ k
            ds, k_blk, (((1,), (0,)), ((), ())), preferred_element_type=jnp.float32
        )
        return dq

    dq = jax.lax.fori_loop(0, num_k_blocks, body, jnp.zeros((block_q, D), jnp.float32))
    dq_ref[:] = (scale * dq).astype(dq_ref.dtype)


def _flash_bwd_dkv_kernel(
    q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, dk_ref, dv_ref,
    *, block_q: int, causal: bool, scale: float,
):
    """Grid: (B*H, Tk//block_k). dk/dv accumulated over contributing q blocks."""
    from jax.experimental import pallas as pl

    block_k, D = k_ref.shape
    Tq = q_ref.shape[0]
    k_blk_idx = pl.program_id(1)
    k = k_ref[:].astype(jnp.float32)
    v = v_ref[:].astype(jnp.float32)
    k_pos = k_blk_idx * block_k + jax.lax.broadcasted_iota(jnp.int32, (1, block_k), 1)

    num_q_blocks = pl.cdiv(Tq, block_q)
    # causal: q blocks strictly above the diagonal contribute nothing
    qb_start = (k_blk_idx * block_k) // block_q if causal else 0

    def body(qb, carry):
        dk, dv = carry
        q_blk = q_ref[pl.ds(qb * block_q, block_q), :].astype(jnp.float32)
        do_blk = do_ref[pl.ds(qb * block_q, block_q), :].astype(jnp.float32)
        lse_blk = lse_ref[pl.ds(qb * block_q, block_q), :][:, :1]
        delta_blk = delta_ref[pl.ds(qb * block_q, block_q), :][:, :1]
        s = scale * jax.lax.dot_general(
            q_blk, k, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        )  # [block_q, block_k]
        if causal:
            q_pos = qb * block_q + jax.lax.broadcasted_iota(jnp.int32, (block_q, 1), 0)
            s = jnp.where(q_pos >= k_pos, s, NEG_INF)
        p = jnp.exp(s - lse_blk)
        dv = dv + jax.lax.dot_general(                        # p^T @ do
            p, do_blk, (((0,), (0,)), ((), ())), preferred_element_type=jnp.float32
        )
        dp = jax.lax.dot_general(                             # do @ v^T
            do_blk, v, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        )
        ds = p * (dp - delta_blk)
        dk = dk + jax.lax.dot_general(                        # ds^T @ q
            ds, q_blk, (((0,), (0,)), ((), ())), preferred_element_type=jnp.float32
        )
        return dk, dv

    zeros = jnp.zeros((block_k, D), jnp.float32)
    dk, dv = jax.lax.fori_loop(qb_start, num_q_blocks, body, (zeros, zeros))
    dk_ref[:] = (scale * dk).astype(dk_ref.dtype)
    dv_ref[:] = dv.astype(dv_ref.dtype)


def _flash_bwd_impl(
    q, k, v, o, lse, do, causal: bool, block_q: int, block_k: int
) -> tuple[jax.Array, jax.Array, jax.Array]:
    """Pallas flash backward: recompute p blockwise from (q, k, lse)."""
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    B, H, Tq, D = q.shape
    Tk = k.shape[2]
    scale = D ** -0.5
    qf = q.reshape(B * H, Tq, D)
    kf = k.reshape(B * H, Tk, D)
    vf = v.reshape(B * H, Tk, D)
    dof = do.reshape(B * H, Tq, D)
    lsef = lse.reshape(B * H, Tq, _STAT_LANES)  # lane-replicated from the fwd
    # delta[i] = rowsum(do ⊙ o): the softmax-normalization term of ds
    delta = jnp.sum(
        dof.astype(jnp.float32) * o.reshape(B * H, Tq, D).astype(jnp.float32), axis=-1
    )
    delta = jnp.broadcast_to(delta[:, :, None], (B * H, Tq, _STAT_LANES))

    full_q = pl.BlockSpec((None, Tq, D), lambda b, i: (b, 0, 0))
    full_k = pl.BlockSpec((None, Tk, D), lambda b, i: (b, 0, 0))
    blk_q = pl.BlockSpec((None, block_q, D), lambda b, i: (b, i, 0))
    blk_k = pl.BlockSpec((None, block_k, D), lambda b, i: (b, i, 0))
    row_q = pl.BlockSpec((None, block_q, _STAT_LANES), lambda b, i: (b, i, 0))
    row_full = pl.BlockSpec((None, Tq, _STAT_LANES), lambda b, i: (b, 0, 0))

    dq = pl.pallas_call(
        functools.partial(_flash_bwd_dq_kernel, block_k=block_k, causal=causal, scale=scale),
        grid=(B * H, Tq // block_q),
        in_specs=[blk_q, full_k, full_k, blk_q, row_q, row_q],
        out_specs=blk_q,
        out_shape=jax.ShapeDtypeStruct((B * H, Tq, D), q.dtype),
        compiler_params=pltpu.CompilerParams(dimension_semantics=("parallel", "arbitrary")),
        interpret=_INTERPRET,
        cost_estimate=pl.CostEstimate(
            flops=6 * B * H * Tq * Tk * D,
            bytes_accessed=3 * (qf.size + kf.size) * q.dtype.itemsize,
            transcendentals=B * H * Tq * Tk,
        ),
    )(qf, kf, vf, dof, lsef, delta)

    dk, dv = pl.pallas_call(
        functools.partial(_flash_bwd_dkv_kernel, block_q=block_q, causal=causal, scale=scale),
        grid=(B * H, Tk // block_k),
        in_specs=[full_q, blk_k, blk_k, full_q, row_full, row_full],
        out_specs=[blk_k, blk_k],
        out_shape=[
            jax.ShapeDtypeStruct((B * H, Tk, D), k.dtype),
            jax.ShapeDtypeStruct((B * H, Tk, D), v.dtype),
        ],
        compiler_params=pltpu.CompilerParams(dimension_semantics=("parallel", "arbitrary")),
        interpret=_INTERPRET,
        cost_estimate=pl.CostEstimate(
            flops=8 * B * H * Tq * Tk * D,
            bytes_accessed=3 * (qf.size + kf.size) * q.dtype.itemsize,
            transcendentals=B * H * Tq * Tk,
        ),
    )(qf, kf, vf, dof, lsef, delta)

    return (
        dq.reshape(B, H, Tq, D),
        dk.reshape(B, H, Tk, D),
        dv.reshape(B, H, Tk, D),
    )


# -- trainable flash attention: pallas forward + pallas backward -------------
# pallas_call has no JVP rule (pallas guide §20: production kernels define a
# custom VJP). The backward is the FlashAttention-2 scheme: forward saves the
# per-row logsumexp; backward recomputes probabilities blockwise in VMEM (two
# kernels: dq over q blocks, dk/dv over k blocks) — no T×T materialization.

_BLOCK_Q, _BLOCK_K = 256, 256


@functools.partial(jax.custom_vjp, nondiff_argnums=(3,))
def _flash_trainable(q, k, v, causal):
    return flash_attention(q, k, v, causal=causal)


def _flash_fwd(q, k, v, causal):
    Tq, Tk = q.shape[2], k.shape[2]
    bq, bk = min(_BLOCK_Q, Tq), min(_BLOCK_K, Tk)
    o, lse = _flash_fwd_lanes(q, k, v, causal, bq, bk)
    return o, (q, k, v, o, lse)


def _flash_bwd(causal, res, g):
    q, k, v, o, lse = res
    Tq, Tk = q.shape[2], k.shape[2]
    bq, bk = min(_BLOCK_Q, Tq), min(_BLOCK_K, Tk)
    return _flash_bwd_impl(q, k, v, o, lse, g, causal, bq, bk)


_flash_trainable.defvjp(_flash_fwd, _flash_bwd)


def mha(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    *,
    causal: bool = True,
    impl: str = "auto",
) -> jax.Array:
    """Dispatcher: Pallas flash kernel on TPU, XLA reference elsewhere."""
    if impl == "auto":
        impl = "flash" if jax.default_backend() not in ("cpu",) else "reference"
    if impl == "flash":
        Tq, Tk = q.shape[2], k.shape[2]
        if Tq % min(256, Tq) == 0 and Tk % min(256, Tk) == 0 and Tq >= 128:
            return _flash_trainable(q, k, v, causal)
    return attention_reference(q, k, v, causal=causal)
