"""Attention kernels: XLA reference + Pallas TPU flash attention.

The compute hot path the reference never owned (it lived inside TF/torch —
SURVEY.md §2.4): here multi-head attention is a first-class op with
- ``attention_reference``: einsum+softmax through XLA (runs everywhere; XLA
  already fuses mask+softmax into the matmuls well on TPU),
- ``flash_attention``: blockwise-online-softmax Pallas kernel keeping the
  score matrix in VMEM tiles (O(T) memory), for long sequences on TPU,
- ``mha``: the dispatcher models call (impl='auto' picks per backend).

GQA/MQA is kernel-native: k/v keep their [B, Hkv, T, D] shape and the
kernels alias q heads onto kv heads through BlockSpec index maps
(head h reads kv head h // n_rep), so K/V HBM traffic stays at Hkv size.
Only the XLA reference path broadcasts (``repeat_kv``).
"""

from __future__ import annotations

import functools
import os

import jax
import jax.numpy as jnp

from tony_tpu.compat import tpu_compiler_params

NEG_INF = -1e30

# Per-row stats (logsumexp, delta) are carried with a trailing lane dim of
# this size: TPU Pallas requires >=2-D tiles whose last dim is 128-divisible
# OR equal to the full array dim — a small full-width lane dim keeps the
# HBM cost of the stats negligible while satisfying the tiling rule.
_STAT_LANES = 8

# CPU tests run the TPU kernels through the Pallas interpreter (the reference
# tests multi-node logic without a cluster; same idea for kernels without a chip)
_INTERPRET = os.environ.get("TONY_PALLAS_INTERPRET", "") == "1"


def repeat_kv(k: jax.Array, n_rep: int) -> jax.Array:
    """[B, Hkv, T, D] → [B, Hkv*n_rep, T, D] (GQA head broadcast)."""
    if n_rep == 1:
        return k
    B, H, T, D = k.shape
    return jnp.broadcast_to(k[:, :, None], (B, H, n_rep, T, D)).reshape(B, H * n_rep, T, D)


def attention_reference(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    *,
    causal: bool = True,
    scale: float | None = None,
    segment_ids: jax.Array | None = None,
    window: int = 0,
) -> jax.Array:
    """Plain attention; q/k/v: [B, H, T, D] (KV already head-broadcast).

    ``segment_ids`` [B, T] (packed sequences): attention is confined within
    each segment — position i attends j only when seg[i] == seg[j].
    ``window`` > 0: sliding-window (Mistral/Mixtral-style) — position i
    attends only the last ``window`` positions (i−window, i].
    """
    D = q.shape[-1]
    scale = scale if scale is not None else D ** -0.5
    s = jnp.einsum("bhqd,bhkd->bhqk", q, k, preferred_element_type=jnp.float32) * scale
    Tq, Tk = s.shape[-2], s.shape[-1]
    q_pos = jnp.arange(Tq)[:, None] + (Tk - Tq)
    k_pos = jnp.arange(Tk)[None, :]
    if causal:
        s = jnp.where(q_pos >= k_pos, s, NEG_INF)
    if window > 0:
        s = jnp.where(q_pos - k_pos < window, s, NEG_INF)
    if segment_ids is not None:
        same = segment_ids[:, None, :, None] == segment_ids[:, None, None, :]
        s = jnp.where(same, s, NEG_INF)
    p = jax.nn.softmax(s, axis=-1)
    return jnp.einsum("bhqk,bhkd->bhqd", p.astype(v.dtype), v)


def _seg_arrays(segment_ids: jax.Array, B: int, T: int) -> tuple[jax.Array, jax.Array]:
    """Lane-/sublane-replicated segment-id layouts the kernels can tile:
    q-side [B, T, _STAT_LANES] (rows) and k-side [B, _STAT_LANES, T] (cols)."""
    s = segment_ids.astype(jnp.int32)
    segq = jnp.broadcast_to(s[:, :, None], (B, T, _STAT_LANES))
    segk = jnp.broadcast_to(s[:, None, :], (B, _STAT_LANES, T))
    return segq, segk


# ---------------------------------------------------------------------------
# Pallas flash attention (TPU)
# ---------------------------------------------------------------------------

def _flash_kernel(
    q_ref, k_ref, v_ref, *rest,
    block_k: int, causal: bool, has_seg: bool, window: int, scale: float,
):
    """Grid: (B*H, Tq//block_q). Online softmax over KV blocks in VMEM.

    Also emits the per-row logsumexp (scaled-score space) so the Pallas
    backward can recompute probabilities blockwise without the T×T matrix.
    With ``has_seg``, two extra refs carry packed-sequence segment ids
    (q-side rows, k-side cols) and scores cross segments are masked.
    ``window`` > 0 adds the sliding-window band: k blocks wholly before the
    window are skipped (no DMA, no flops), partial blocks are masked.
    """
    from jax.experimental import pallas as pl

    if has_seg:
        segq_ref, segk_ref, o_ref, lse_ref = rest
    else:
        o_ref, lse_ref = rest
    block_q, D = q_ref.shape
    Tk = k_ref.shape[0]
    q_blk_idx = pl.program_id(1)
    q = q_ref[:] .astype(jnp.float32) * scale
    q_pos = q_blk_idx * block_q + jax.lax.broadcasted_iota(jnp.int32, (block_q, 1), 0)
    sq = segq_ref[:][:, :1] if has_seg else None  # [block_q, 1]

    num_k_blocks = pl.cdiv(Tk, block_k)
    kb_start = 0
    if causal:
        # only blocks at or below the diagonal contribute
        num_k_blocks = jnp.minimum(num_k_blocks, (q_blk_idx + 1) * block_q // block_k + 1)
    if window > 0:
        # first k position any row of this q block can see: q_first−window+1
        kb_start = jnp.maximum(0, (q_blk_idx * block_q - window + 1) // block_k)

    def body(kb, carry):
        o, m, l = carry
        k_blk = k_ref[pl.ds(kb * block_k, block_k), :].astype(jnp.float32)
        v_blk = v_ref[pl.ds(kb * block_k, block_k), :].astype(jnp.float32)
        s = jax.lax.dot_general(
            q, k_blk, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        )  # [block_q, block_k]
        k_pos = kb * block_k + jax.lax.broadcasted_iota(jnp.int32, (1, block_k), 1)
        if causal:
            s = jnp.where(q_pos >= k_pos, s, NEG_INF)
        if window > 0:
            s = jnp.where(q_pos - k_pos < window, s, NEG_INF)
        if has_seg:
            sk = segk_ref[:1, pl.ds(kb * block_k, block_k)]  # [1, block_k]
            s = jnp.where(sq == sk, s, NEG_INF)
        m_b = jnp.max(s, axis=-1, keepdims=True)
        m_new = jnp.maximum(m, m_b)
        alpha = jnp.exp(m - m_new)
        p = jnp.exp(s - m_new)
        l_new = l * alpha + jnp.sum(p, axis=-1, keepdims=True)
        o_new = o * alpha + jax.lax.dot_general(
            p, v_blk, (((1,), (0,)), ((), ())), preferred_element_type=jnp.float32
        )
        return o_new, m_new, l_new

    o0 = jnp.zeros((block_q, D), jnp.float32)
    m0 = jnp.full((block_q, 1), NEG_INF, jnp.float32)
    l0 = jnp.zeros((block_q, 1), jnp.float32)
    o, m, l = jax.lax.fori_loop(kb_start, num_k_blocks, body, (o0, m0, l0))
    l = jnp.maximum(l, 1e-20)
    o_ref[:] = (o / l).astype(o_ref.dtype)
    lse_ref[:] = jnp.broadcast_to(m + jnp.log(l), (block_q, _STAT_LANES))


def _flash_fwd_impl(
    q: jax.Array, k: jax.Array, v: jax.Array, causal: bool, block_q: int, block_k: int,
    segment_ids: jax.Array | None = None, window: int = 0,
) -> tuple[jax.Array, jax.Array]:
    """Shared forward: ([B,H,Tq,D], lse [B,H,Tq]) — shapes pre-validated."""
    out, lse_lanes = _flash_fwd_lanes(q, k, v, causal, block_q, block_k, segment_ids, window)
    return out, lse_lanes[:, :, :, 0]


def _flash_fwd_lanes(
    q: jax.Array, k: jax.Array, v: jax.Array, causal: bool, block_q: int, block_k: int,
    segment_ids: jax.Array | None = None, window: int = 0,
) -> tuple[jax.Array, jax.Array]:
    """Forward returning the lane-replicated lse [B,H,Tq,_STAT_LANES] so the
    backward can feed it to the Pallas kernels without a re-broadcast.

    GQA is kernel-native: k/v arrive as [B, Hkv, Tk, D] and the q-head grid
    aliases onto kv heads through the BlockSpec index map (head h reads kv
    head h // n_rep) — no head broadcast, so K/V HBM traffic stays at the
    Hkv size. Consecutive q heads map to the same kv block, which Pallas
    recognizes as a revisit and keeps resident in VMEM.
    """
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    B, H, Tq, D = q.shape
    Hkv, Tk = k.shape[1], k.shape[2]
    n_rep = H // Hkv
    scale = D ** -0.5
    qf = q.reshape(B * H, Tq, D)
    kf = k.reshape(B * Hkv, Tk, D)
    vf = v.reshape(B * Hkv, Tk, D)

    has_seg = segment_ids is not None
    kernel = functools.partial(
        _flash_kernel, block_k=block_k, causal=causal, has_seg=has_seg,
        window=window, scale=scale,
    )
    in_specs = [
        pl.BlockSpec((None, block_q, D), lambda b, i: (b, i, 0)),
        pl.BlockSpec((None, Tk, D), lambda b, i: (b // n_rep, 0, 0)),
        pl.BlockSpec((None, Tk, D), lambda b, i: (b // n_rep, 0, 0)),
    ]
    operands = [qf, kf, vf]
    if has_seg:
        segq, segk = _seg_arrays(segment_ids, B, Tq)
        in_specs += [
            pl.BlockSpec((None, block_q, _STAT_LANES), lambda b, i: (b // H, i, 0)),
            pl.BlockSpec((None, _STAT_LANES, Tk), lambda b, i: (b // H, 0, 0)),
        ]
        operands += [segq, segk]
    out, lse = pl.pallas_call(
        kernel,
        grid=(B * H, Tq // block_q),
        in_specs=in_specs,
        out_specs=[
            pl.BlockSpec((None, block_q, D), lambda b, i: (b, i, 0)),
            pl.BlockSpec((None, block_q, _STAT_LANES), lambda b, i: (b, i, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((B * H, Tq, D), q.dtype),
            jax.ShapeDtypeStruct((B * H, Tq, _STAT_LANES), jnp.float32),
        ],
        compiler_params=tpu_compiler_params(
            dimension_semantics=("parallel", "arbitrary"),
        ),
        interpret=_INTERPRET,
        cost_estimate=pl.CostEstimate(
            flops=4 * B * H * Tq * Tk * D,
            bytes_accessed=2 * (qf.size + kf.size + vf.size) * q.dtype.itemsize,
            transcendentals=B * H * Tq * Tk,
        ),
    )(*operands)
    return out.reshape(B, H, Tq, D), lse.reshape(B, H, Tq, _STAT_LANES)


@functools.partial(jax.jit, static_argnames=("causal", "block_q", "block_k", "window"))
def flash_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    *,
    causal: bool = True,
    block_q: int | None = None,
    block_k: int | None = None,
    segment_ids: jax.Array | None = None,
    window: int = 0,
) -> jax.Array:
    """Pallas TPU flash attention; q: [B, H, T, D], k/v: [B, Hkv, T, D] with
    H % Hkv == 0 (GQA handled inside the kernel), T % block == 0.
    ``segment_ids`` [B, T] confines attention within packed segments
    (training-shape only: Tq == Tk). ``window`` > 0: sliding-window band —
    out-of-band k blocks are skipped entirely (no DMA, no flops).
    ``block_q``/``block_k`` default to the tuned module constants, shrunk
    to divide the sequence lengths (``_block_sizes``)."""
    B, H, Tq, D = q.shape
    Hkv, Tk = k.shape[1], k.shape[2]
    if H % Hkv:
        raise ValueError(f"n_heads {H} must be divisible by n_kv_heads {Hkv}")
    if segment_ids is not None and Tq != Tk:
        raise ValueError(f"segment_ids requires Tq == Tk, got {Tq} vs {Tk}")
    auto_bq, auto_bk = _tuned_blocks("flash_fwd", q, Hkv, Tk)
    block_q = auto_bq if block_q is None else min(block_q, Tq)
    block_k = auto_bk if block_k is None else min(block_k, Tk)
    # awkward lengths (e.g. 257) make _block_sizes halve to degenerate
    # blocks — take the XLA reference path rather than a laneless grid.
    # Non-8-multiple blocks (a 300-long seq reaching the kernel as one
    # block) are a Mosaic sublane-alignment lowering risk the interpreter
    # won't catch — route them to the reference path too.
    if (block_q < min(8, Tq) or block_k < min(128, Tk)
            or block_q % 8 or block_k % 8):
        return attention_reference(
            q, repeat_kv(k, H // Hkv), repeat_kv(v, H // Hkv),
            causal=causal, segment_ids=segment_ids, window=window,
        )
    if Tq % block_q or Tk % block_k:
        return attention_reference(
            q, repeat_kv(k, H // Hkv), repeat_kv(v, H // Hkv),
            causal=causal, segment_ids=segment_ids, window=window,
        )
    return _flash_fwd_impl(q, k, v, causal, block_q, block_k, segment_ids, window)[0]


def _flash_bwd_dq_kernel(
    q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, *rest,
    block_k: int, causal: bool, has_seg: bool, window: int, scale: float,
):
    """Grid: (B*H, Tq//block_q). dq[i] = scale · Σ_kb ds[i,kb] @ k[kb]."""
    from jax.experimental import pallas as pl

    if has_seg:
        segq_ref, segk_ref, dq_ref = rest
    else:
        (dq_ref,) = rest
    block_q, D = q_ref.shape
    Tk = k_ref.shape[0]
    q_blk_idx = pl.program_id(1)
    q = q_ref[:].astype(jnp.float32)
    do = do_ref[:].astype(jnp.float32)
    lse = lse_ref[:][:, :1]            # [block_q, 1] (lanes identical)
    delta = delta_ref[:][:, :1]        # [block_q, 1]
    q_pos = q_blk_idx * block_q + jax.lax.broadcasted_iota(jnp.int32, (block_q, 1), 0)
    sq = segq_ref[:][:, :1] if has_seg else None

    num_k_blocks = pl.cdiv(Tk, block_k)
    kb_start = 0
    if causal:
        num_k_blocks = jnp.minimum(num_k_blocks, (q_blk_idx + 1) * block_q // block_k + 1)
    if window > 0:
        kb_start = jnp.maximum(0, (q_blk_idx * block_q - window + 1) // block_k)

    def body(kb, dq):
        k_blk = k_ref[pl.ds(kb * block_k, block_k), :].astype(jnp.float32)
        v_blk = v_ref[pl.ds(kb * block_k, block_k), :].astype(jnp.float32)
        s = scale * jax.lax.dot_general(
            q, k_blk, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        )
        k_pos = kb * block_k + jax.lax.broadcasted_iota(jnp.int32, (1, block_k), 1)
        if causal:
            s = jnp.where(q_pos >= k_pos, s, NEG_INF)
        if window > 0:
            s = jnp.where(q_pos - k_pos < window, s, NEG_INF)
        if has_seg:
            sk = segk_ref[:1, pl.ds(kb * block_k, block_k)]
            s = jnp.where(sq == sk, s, NEG_INF)
        p = jnp.exp(s - lse)                                   # [block_q, block_k]
        dp = jax.lax.dot_general(                              # do @ v^T
            do, v_blk, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        )
        ds = p * (dp - delta)
        dq = dq + jax.lax.dot_general(                         # ds @ k
            ds, k_blk, (((1,), (0,)), ((), ())), preferred_element_type=jnp.float32
        )
        return dq

    dq = jax.lax.fori_loop(kb_start, num_k_blocks, body, jnp.zeros((block_q, D), jnp.float32))
    dq_ref[:] = (scale * dq).astype(dq_ref.dtype)


def _dkv_block_contrib(
    q_blk, do_blk, lse_blk, delta_blk, k, v, q_pos, k_pos, causal, scale,
    sq=None, sk=None, window: int = 0,
):
    """One q-block's contribution to (dk, dv) for one k block — the shared
    gradient math of both dkv variants (they differ only in data staging).
    Returns dk WITHOUT the final `scale` factor (callers apply it)."""
    s = scale * jax.lax.dot_general(
        q_blk, k, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
    )  # [block_q, block_k]
    if causal:
        s = jnp.where(q_pos >= k_pos, s, NEG_INF)
    if window > 0:
        s = jnp.where(q_pos - k_pos < window, s, NEG_INF)
    if sq is not None:
        s = jnp.where(sq == sk, s, NEG_INF)
    p = jnp.exp(s - lse_blk)
    dv_c = jax.lax.dot_general(                    # p^T @ do
        p, do_blk, (((0,), (0,)), ((), ())), preferred_element_type=jnp.float32
    )
    dp = jax.lax.dot_general(                      # do @ v^T
        do_blk, v, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
    )
    ds = p * (dp - delta_blk)
    dk_c = jax.lax.dot_general(                    # ds^T @ q
        ds, q_blk, (((0,), (0,)), ((), ())), preferred_element_type=jnp.float32
    )
    return dk_c, dv_c


def _flash_bwd_dkv_kernel_resident(
    q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, *rest,
    block_q: int, n_rep: int, causal: bool, has_seg: bool, window: int, scale: float,
):
    """Grid: (B*Hkv, Tk//block_k) with the whole [n_rep·Tq, D] q/do staged in
    VMEM — the fast variant for moderate sequence lengths: causally-skipped
    q blocks cost neither DMA nor flops (the fori_loop starts at the
    diagonal). Selected when the staged operands fit the VMEM budget."""
    from jax.experimental import pallas as pl

    if has_seg:
        segq_ref, segk_ref, dk_ref, dv_ref = rest
    else:
        dk_ref, dv_ref = rest
    block_k, D = k_ref.shape
    Tq = q_ref.shape[0] // n_rep
    k_blk_idx = pl.program_id(1)
    k = k_ref[:].astype(jnp.float32)
    v = v_ref[:].astype(jnp.float32)
    k_pos = k_blk_idx * block_k + jax.lax.broadcasted_iota(jnp.int32, (1, block_k), 1)
    sk = segk_ref[:1, :] if has_seg else None  # [1, block_k] (this k block)

    num_q_blocks = pl.cdiv(Tq, block_q)
    qb_start = (k_blk_idx * block_k) // block_q if causal else 0
    qb_end = num_q_blocks
    if window > 0:
        # rows beyond the window of this k block's LAST position contribute 0
        last_k = k_blk_idx * block_k + block_k - 1
        qb_end = jnp.minimum(num_q_blocks, (last_k + window - 1) // block_q + 1)

    def make_body(g_off: int):
        def body(qb, carry):
            dk, dv = carry
            q_blk = q_ref[pl.ds(g_off + qb * block_q, block_q), :].astype(jnp.float32)
            do_blk = do_ref[pl.ds(g_off + qb * block_q, block_q), :].astype(jnp.float32)
            lse_blk = lse_ref[pl.ds(g_off + qb * block_q, block_q), :][:, :1]
            delta_blk = delta_ref[pl.ds(g_off + qb * block_q, block_q), :][:, :1]
            q_pos = qb * block_q + jax.lax.broadcasted_iota(jnp.int32, (block_q, 1), 0)
            # seg rows are PER HEAD (not group-folded): index by qb directly
            sq = segq_ref[pl.ds(qb * block_q, block_q), :][:, :1] if has_seg else None
            dk_c, dv_c = _dkv_block_contrib(
                q_blk, do_blk, lse_blk, delta_blk, k, v, q_pos, k_pos, causal, scale,
                sq, sk, window,
            )
            return dk + dk_c, dv + dv_c

        return body

    zeros = jnp.zeros((block_k, D), jnp.float32)
    dk, dv = zeros, zeros
    for g in range(n_rep):  # static group unroll
        dk, dv = jax.lax.fori_loop(qb_start, qb_end, make_body(g * Tq), (dk, dv))
    dk_ref[:] = (scale * dk).astype(dk_ref.dtype)
    dv_ref[:] = dv.astype(dv_ref.dtype)


# staged q/do bytes (bf16, double-buffered) beyond which the resident dkv
# variant would exceed the ~16M scoped-VMEM budget → use the streaming grid
_DKV_RESIDENT_MAX_QROWS = 4096


def _flash_bwd_dkv_kernel(
    kb_ref, qrow_ref, q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, *rest,
    num_q_blocks: int, causal: bool, has_seg: bool, window: int, scale: float,
):
    """Grid: (B*Hkv, n_pairs) — one causally-contributing (k block, q block)
    pair per step, streamed via scalar-prefetched index arrays.

    Only one q block is staged in VMEM per step (long sequences would blow
    the VMEM budget if the whole [n_rep·Tq, D] q were staged, as an earlier
    design did), and — unlike a dense (k block × q block) grid — pairs above
    the causal diagonal are never enumerated, so they cost neither DMA nor a
    grid step. dk/dv output blocks are revisited across consecutive pairs of
    the same k block (pairs are sorted by k block), accumulating in f32 in
    VMEM; GQA group members are folded into the q dim (layout
    [B*Hkv, n_rep*Tq, …]), so each pair's q-block index within its own head
    (for position masking) is ``qrow % num_q_blocks``.
    """
    from jax.experimental import pallas as pl

    if has_seg:
        segq_ref, segk_ref, dk_ref, dv_ref = rest
    else:
        dk_ref, dv_ref = rest
    block_q = q_ref.shape[0]
    block_k = k_ref.shape[0]
    j = pl.program_id(1)
    k_blk_idx = kb_ref[j]
    qb = qrow_ref[j] % num_q_blocks  # q-block index within this member's head
    first = jnp.logical_or(j == 0, k_blk_idx != kb_ref[jnp.maximum(j - 1, 0)])

    @pl.when(first)
    def _init():
        dk_ref[:] = jnp.zeros_like(dk_ref)
        dv_ref[:] = jnp.zeros_like(dv_ref)

    k = k_ref[:].astype(jnp.float32)
    v = v_ref[:].astype(jnp.float32)
    k_pos = k_blk_idx * block_k + jax.lax.broadcasted_iota(jnp.int32, (1, block_k), 1)
    q_blk = q_ref[:].astype(jnp.float32)
    do_blk = do_ref[:].astype(jnp.float32)
    lse_blk = lse_ref[:][:, :1]
    delta_blk = delta_ref[:][:, :1]
    q_pos = qb * block_q + jax.lax.broadcasted_iota(jnp.int32, (block_q, 1), 0)
    sq = segq_ref[:][:, :1] if has_seg else None
    sk = segk_ref[:1, :] if has_seg else None
    dk_c, dv_c = _dkv_block_contrib(
        q_blk, do_blk, lse_blk, delta_blk, k, v, q_pos, k_pos, causal, scale, sq, sk, window
    )
    dk_ref[:] += scale * dk_c
    dv_ref[:] += dv_c


def _flash_bwd_impl(
    q, k, v, o, lse, do, causal: bool, block_q: int, block_k: int,
    segment_ids: jax.Array | None = None, window: int = 0,
) -> tuple[jax.Array, jax.Array, jax.Array]:
    """Pallas flash backward: recompute p blockwise from (q, k, lse)."""
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    B, H, Tq, D = q.shape
    Hkv, Tk = k.shape[1], k.shape[2]
    n_rep = H // Hkv
    scale = D ** -0.5
    qf = q.reshape(B * H, Tq, D)
    kf = k.reshape(B * Hkv, Tk, D)
    vf = v.reshape(B * Hkv, Tk, D)
    dof = do.reshape(B * H, Tq, D)
    lsef = lse.reshape(B * H, Tq, _STAT_LANES)  # lane-replicated from the fwd
    # delta[i] = rowsum(do ⊙ o): the softmax-normalization term of ds
    delta = jnp.sum(
        dof.astype(jnp.float32) * o.reshape(B * H, Tq, D).astype(jnp.float32), axis=-1
    )
    delta = jnp.broadcast_to(delta[:, :, None], (B * H, Tq, _STAT_LANES))

    full_k = pl.BlockSpec((None, Tk, D), lambda b, i: (b // n_rep, 0, 0))
    blk_q = pl.BlockSpec((None, block_q, D), lambda b, i: (b, i, 0))
    blk_k = pl.BlockSpec((None, block_k, D), lambda b, i: (b, i, 0))
    row_q = pl.BlockSpec((None, block_q, _STAT_LANES), lambda b, i: (b, i, 0))

    has_seg = segment_ids is not None
    if has_seg:
        segq, segk = _seg_arrays(segment_ids, B, Tq)  # Tq == Tk (validated)

    dq_specs = [blk_q, full_k, full_k, blk_q, row_q, row_q]
    dq_operands = [qf, kf, vf, dof, lsef, delta]
    if has_seg:
        dq_specs += [
            pl.BlockSpec((None, block_q, _STAT_LANES), lambda b, i: (b // H, i, 0)),
            pl.BlockSpec((None, _STAT_LANES, Tk), lambda b, i: (b // H, 0, 0)),
        ]
        dq_operands += [segq, segk]
    dq = pl.pallas_call(
        functools.partial(
            _flash_bwd_dq_kernel, block_k=block_k, causal=causal, has_seg=has_seg,
            window=window, scale=scale,
        ),
        grid=(B * H, Tq // block_q),
        in_specs=dq_specs,
        out_specs=blk_q,
        out_shape=jax.ShapeDtypeStruct((B * H, Tq, D), q.dtype),
        compiler_params=tpu_compiler_params(dimension_semantics=("parallel", "arbitrary")),
        interpret=_INTERPRET,
        cost_estimate=pl.CostEstimate(
            flops=6 * B * H * Tq * Tk * D,
            bytes_accessed=3 * (qf.size + kf.size) * q.dtype.itemsize,
            transcendentals=B * H * Tq * Tk,
        ),
    )(*dq_operands)

    # dk/dv: grid over (kv head, k block, group-member × q block); the GQA
    # group is folded into the q dim (layout [B*Hkv, n_rep*Tq, …]) and the
    # innermost grid dim walks one q block at a time — O(block) VMEM at any
    # sequence length, with dk/dv blocks revisited and accumulated in f32.
    num_q_blocks = Tq // block_q
    qg = qf.reshape(B * Hkv, n_rep * Tq, D)
    dog = dof.reshape(B * Hkv, n_rep * Tq, D)
    lseg = lsef.reshape(B * Hkv, n_rep * Tq, _STAT_LANES)
    deltag = delta.reshape(B * Hkv, n_rep * Tq, _STAT_LANES)
    blk_kv2 = pl.BlockSpec((None, block_k, D), lambda b, i: (b, i, 0))
    cost = pl.CostEstimate(
        flops=8 * B * H * Tq * Tk * D,
        bytes_accessed=3 * (qf.size + kf.size) * q.dtype.itemsize,
        transcendentals=B * H * Tq * Tk,
    )

    if n_rep * Tq <= _DKV_RESIDENT_MAX_QROWS:
        full_qg = pl.BlockSpec((None, n_rep * Tq, D), lambda b, i: (b, 0, 0))
        row_full_g = pl.BlockSpec((None, n_rep * Tq, _STAT_LANES), lambda b, i: (b, 0, 0))
        dkv_specs = [full_qg, blk_kv2, blk_kv2, full_qg, row_full_g, row_full_g]
        dkv_operands = [qg, kf, vf, dog, lseg, deltag]
        if has_seg:
            dkv_specs += [
                # per-head q rows (NOT group-folded; kernel indexes by qb)
                pl.BlockSpec((None, Tq, _STAT_LANES), lambda b, i: (b // Hkv, 0, 0)),
                pl.BlockSpec((None, _STAT_LANES, block_k), lambda b, i: (b // Hkv, 0, i)),
            ]
            dkv_operands += [segq, segk]
        dk, dv = pl.pallas_call(
            functools.partial(
                _flash_bwd_dkv_kernel_resident,
                block_q=block_q, n_rep=n_rep, causal=causal, has_seg=has_seg,
                window=window, scale=scale,
            ),
            grid=(B * Hkv, Tk // block_k),
            in_specs=dkv_specs,
            out_specs=[blk_kv2, blk_kv2],
            out_shape=[
                jax.ShapeDtypeStruct((B * Hkv, Tk, D), k.dtype),
                jax.ShapeDtypeStruct((B * Hkv, Tk, D), v.dtype),
            ],
            compiler_params=tpu_compiler_params(
                dimension_semantics=("parallel", "arbitrary")
            ),
            interpret=_INTERPRET,
            cost_estimate=cost,
        )(*dkv_operands)
    else:
        # streaming grid: enumerate only the causally-contributing
        # (k block, group member, q block) pairs, sorted by k block, and
        # scalar-prefetch the index arrays so BlockSpec index maps (and the
        # DMA pipeline) follow the sparse walk — q blocks above the diagonal
        # are never fetched, halving DMA traffic and grid steps for causal.
        kb_l, qrow_l = [], []
        for i in range(Tk // block_k):
            # fully-masked k blocks (possible when Tk > Tq) still emit ONE
            # q block per group member: its contribution is exactly zero
            # through the mask, but the visit zero-initializes the output
            # block, which would otherwise be returned uninitialized
            qb0 = min((i * block_k) // block_q, num_q_blocks - 1) if causal else 0
            qb1 = num_q_blocks
            if window > 0:
                # q rows past this k block's window band contribute nothing
                last_k = i * block_k + block_k - 1
                qb1 = max(min(num_q_blocks, (last_k + window - 1) // block_q + 1), qb0 + 1)
            for g in range(n_rep):
                for qb in range(qb0, qb1):
                    kb_l.append(i)
                    qrow_l.append(g * num_q_blocks + qb)
        kb = jnp.array(kb_l, dtype=jnp.int32)
        qrow = jnp.array(qrow_l, dtype=jnp.int32)
        n_pairs = len(kb_l)
        # the sparse walk does `frac` of the dense grid's work (~1/2 causal)
        frac = n_pairs / ((Tk // block_k) * n_rep * num_q_blocks)
        cost = pl.CostEstimate(
            flops=int(cost.flops * frac),
            bytes_accessed=int(cost.bytes_accessed * frac),
            transcendentals=int(cost.transcendentals * frac),
        )

        def q_map(b, j, kb_r, qrow_r):
            return (b, qrow_r[j], 0)

        def kv_map(b, j, kb_r, qrow_r):
            return (b, kb_r[j], 0)

        stream_specs = [
            pl.BlockSpec((None, block_q, D), q_map),
            pl.BlockSpec((None, block_k, D), kv_map),
            pl.BlockSpec((None, block_k, D), kv_map),
            pl.BlockSpec((None, block_q, D), q_map),
            pl.BlockSpec((None, block_q, _STAT_LANES), q_map),
            pl.BlockSpec((None, block_q, _STAT_LANES), q_map),
        ]
        stream_operands = [qg, kf, vf, dog, lseg, deltag]
        if has_seg:
            # seg arrays are [B, ...] per-head (not group-folded): batch =
            # b // Hkv, q block within head = qrow % num_q_blocks
            stream_specs += [
                pl.BlockSpec(
                    (None, block_q, _STAT_LANES),
                    lambda b, j, kb_r, qrow_r: (b // Hkv, qrow_r[j] % num_q_blocks, 0),
                ),
                pl.BlockSpec(
                    (None, _STAT_LANES, block_k),
                    lambda b, j, kb_r, qrow_r: (b // Hkv, 0, kb_r[j]),
                ),
            ]
            stream_operands += [segq, segk]
        grid_spec = pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=2,
            grid=(B * Hkv, n_pairs),
            in_specs=stream_specs,
            out_specs=[
                pl.BlockSpec((None, block_k, D), kv_map),
                pl.BlockSpec((None, block_k, D), kv_map),
            ],
        )
        dk, dv = pl.pallas_call(
            functools.partial(
                _flash_bwd_dkv_kernel,
                num_q_blocks=num_q_blocks, causal=causal, has_seg=has_seg,
                window=window, scale=scale,
            ),
            grid_spec=grid_spec,
            out_shape=[
                jax.ShapeDtypeStruct((B * Hkv, Tk, D), jnp.float32),
                jax.ShapeDtypeStruct((B * Hkv, Tk, D), jnp.float32),
            ],
            compiler_params=tpu_compiler_params(
                dimension_semantics=("parallel", "arbitrary")
            ),
            interpret=_INTERPRET,
            cost_estimate=cost,
        )(kb, qrow, *stream_operands)

    return (
        dq.reshape(B, H, Tq, D),
        dk.reshape(B, Hkv, Tk, D).astype(k.dtype),
        dv.reshape(B, Hkv, Tk, D).astype(v.dtype),
    )


# -- trainable flash attention: pallas forward + pallas backward -------------
# pallas_call has no JVP rule (pallas guide §20: production kernels define a
# custom VJP). The backward is the FlashAttention-2 scheme: forward saves the
# per-row logsumexp; backward recomputes probabilities blockwise in VMEM (two
# kernels: dq over q blocks, dk/dv over k blocks) — no T×T materialization.

# bq 256 / bk 512: the r3 measured optimum on v5e — halving k-block count
# beats 256/256 on EVERY bench preset, same-session A/Bs: llama-0.87B
# 46.5→49.0% MFU, llama 2×8192 38.4→46.1%, moe 35.3→37.0%, BERT 34.5→37.7%.
# (512/512 and bk 1024 fail to compile — VMEM; bq 128 is neutral.)
# Env-overridable for per-hardware tuning; BASELINE.md records the ladder.
_BLOCK_Q = int(os.environ.get("TONY_FLASH_BQ", "256"))
_BLOCK_K = int(os.environ.get("TONY_FLASH_BK", "512"))
if _BLOCK_Q < 8 or _BLOCK_Q % 8:
    raise ValueError(f"TONY_FLASH_BQ must be a multiple of 8 >= 8, got {_BLOCK_Q}")
if _BLOCK_K < 128 or _BLOCK_K % 128:
    raise ValueError(f"TONY_FLASH_BK must be a multiple of 128 >= 128, got {_BLOCK_K}")


def _block_sizes(Tq: int, Tk: int) -> tuple[int, int]:
    """Largest blocks ≤ the configured defaults that DIVIDE the sequence
    lengths (halving until they do). With bq ≠ bk defaults, a length like
    768 divides 256 but not 512 — every kernel entry point must agree on
    this rule or the grid reads padded garbage past the last block."""
    bq, bk = min(_BLOCK_Q, Tq), min(_BLOCK_K, Tk)
    while bq > 1 and Tq % bq:
        bq //= 2
    while bk > 1 and Tk % bk:
        bk //= 2
    # Mosaic sublane alignment: a non-8-multiple block (Tq=132 → bq=132
    # divides but can't lower cleanly) is a hardware lowering risk. Degrade
    # it to 1 so EVERY caller's small-block fallback gate — including the
    # custom_vjp training entry points, which don't re-check alignment —
    # routes such shapes to the XLA reference path.
    if bq % 8:
        bq = 1
    if bk % 8:
        bk = 1
    return bq, bk


def _tuned_blocks(op: str, q: jax.Array, kv_heads: int, Tk: int) -> tuple[int, int]:
    """Autotuner-aware block sizes: an ops/tune.py cache hit for this exact
    (device, geometry, dtype) — validated against the kernels' lowering
    preconditions, so a stale entry degrades to the default instead of a
    Mosaic failure — else the tuned module constants via ``_block_sizes``.
    Trace-time only (the blocks are static kernel parameters)."""
    B, H, Tq, D = (int(d) for d in q.shape)
    if "TONY_FLASH_BQ" in os.environ or "TONY_FLASH_BK" in os.environ:
        # an EXPLICIT env override is the operator's debugging lever — it
        # must beat the tune cache (which otherwise wins silently)
        return _block_sizes(Tq, Tk)
    from tony_tpu.ops import tune

    params = tune.lookup(op, (B, H, int(kv_heads), Tq, int(Tk), D), str(q.dtype))
    if params:
        bq, bk = int(params.get("block_q", 0)), int(params.get("block_k", 0))
        if (bq >= 8 and bk >= 128 and not (bq % 8 or bk % 128)
                and not (Tq % bq or Tk % bk)):
            return bq, bk
    return _block_sizes(Tq, Tk)


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4))
def _flash_trainable(q, k, v, causal, window=0):
    return flash_attention(q, k, v, causal=causal, window=window)


def _flash_fwd(q, k, v, causal, window):
    from jax.ad_checkpoint import checkpoint_name

    Tq, Tk = q.shape[2], k.shape[2]
    bq, bk = _tuned_blocks("flash_fwd", q, k.shape[1], Tk)
    o, lse = _flash_fwd_lanes(q, k, v, causal, bq, bk, None, window)
    # Named so a remat policy can pin JUST the kernel outputs
    # (save_only_these_names("flash_o", "flash_lse")): the backward then
    # recomputes the cheap qkv matmuls but not the O(T²) flash forward.
    o = checkpoint_name(o, "flash_o")
    lse = checkpoint_name(lse, "flash_lse")
    return o, (q, k, v, o, lse)


def _flash_bwd(causal, window, res, g):
    q, k, v, o, lse = res
    Tq, Tk = q.shape[2], k.shape[2]
    bq, bk = _tuned_blocks("flash_bwd", q, k.shape[1], Tk)
    return _flash_bwd_impl(q, k, v, o, lse, g, causal, bq, bk, None, window)


_flash_trainable.defvjp(_flash_fwd, _flash_bwd)


@functools.partial(jax.custom_vjp, nondiff_argnums=(4, 5))
def _flash_trainable_seg(q, k, v, seg, causal, window=0):
    """Packed-sequence variant: seg [B, T] int; cotangent for seg is float0."""
    bq, bk = _tuned_blocks("flash_fwd", q, k.shape[1], k.shape[2])
    return _flash_fwd_impl(q, k, v, causal, bq, bk, seg, window)[0]


def _flash_seg_fwd(q, k, v, seg, causal, window):
    from jax.ad_checkpoint import checkpoint_name

    Tq, Tk = q.shape[2], k.shape[2]
    bq, bk = _tuned_blocks("flash_fwd", q, k.shape[1], Tk)
    o, lse = _flash_fwd_lanes(q, k, v, causal, bq, bk, seg, window)
    o = checkpoint_name(o, "flash_o")
    lse = checkpoint_name(lse, "flash_lse")
    return o, (q, k, v, seg, o, lse)


def _flash_seg_bwd(causal, window, res, g):
    import numpy as np

    q, k, v, seg, o, lse = res
    Tq, Tk = q.shape[2], k.shape[2]
    bq, bk = _tuned_blocks("flash_bwd", q, k.shape[1], Tk)
    dq, dk, dv = _flash_bwd_impl(q, k, v, o, lse, g, causal, bq, bk, seg, window)
    return dq, dk, dv, np.zeros(seg.shape, jax.dtypes.float0)


_flash_trainable_seg.defvjp(_flash_seg_fwd, _flash_seg_bwd)


def remat_block(block_fn, remat: bool, policy: str = "full"):
    """Wrap a scanned decoder block in the configured remat policy.

    Lives here because the "flash" policy pins THIS module's checkpoint
    names (flash_o / flash_lse from _flash_fwd) — models must not hardcode
    them. Policies: "full" (recompute everything), "dots" (save matmul
    outputs), "flash" (save only the flash-kernel outputs so the backward
    never replays the O(T²) forward kernel).
    """
    if not remat:
        return block_fn
    if policy == "dots":
        return jax.checkpoint(
            block_fn, policy=jax.checkpoint_policies.dots_with_no_batch_dims_saveable
        )
    if policy == "flash":
        # also pins MoE routing outputs (parallel/expert.py names them
        # "moe_route": tiny tensors whose recompute would re-run the whole
        # vector-bound gating pipeline) and the fused expert-MLP kernel
        # output ("moe_gemm", ops/moe_gemm.py): [N_rows, D] bf16 per layer
        # — the one activation whose replay would re-run three grouped
        # GEMMs (A/B'd +0.8 MFU pt on the moe bench preset, BASELINE.md r3).
        # TONY_REMAT_EXTRA_NAMES ("a,b") appends further named activations
        # (e.g. moe_disp / moe_combine) — the measurement ladder's knob for
        # per-shape save-vs-replay tradeoffs without code edits.
        names = ["flash_o", "flash_lse", "moe_route", "moe_gemm"]
        extra = os.environ.get("TONY_REMAT_EXTRA_NAMES", "")
        names += [n.strip() for n in extra.split(",") if n.strip()]
        return jax.checkpoint(
            block_fn,
            policy=jax.checkpoint_policies.save_only_these_names(*names),
        )
    if policy != "full":
        raise ValueError(f"remat_policy must be full|dots|flash, got {policy!r}")
    return jax.checkpoint(block_fn)


def mha(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    *,
    causal: bool = True,
    impl: str = "auto",
    segment_ids: jax.Array | None = None,
    window: int = 0,
) -> jax.Array:
    """Dispatcher: Pallas flash kernel on TPU, XLA reference elsewhere.

    k/v may carry fewer heads than q (GQA/MQA): the flash kernels read kv
    heads in place via index-map aliasing; the reference path broadcasts.
    ``segment_ids`` [B, T] confines attention within packed segments.
    ``window`` > 0: sliding-window (Mistral/Mixtral) attention band.
    """
    if q.shape[1] % k.shape[1]:
        raise ValueError(f"n_heads {q.shape[1]} must be divisible by n_kv_heads {k.shape[1]}")
    n_rep = q.shape[1] // k.shape[1]
    if impl == "auto":
        impl = "flash" if jax.default_backend() not in ("cpu",) else "reference"
    if impl == "flash":
        Tq, Tk = q.shape[2], k.shape[2]
        bq, bk = _block_sizes(Tq, Tk)
        # ragged lengths shrink the blocks; below 128 the kernel grid is
        # lane-starved and the XLA reference path wins
        if bq >= 128 and bk >= 128 and Tq >= 128:
            if segment_ids is not None:
                if Tq != Tk:
                    raise ValueError(f"segment_ids requires Tq == Tk, got {Tq} vs {Tk}")
                return _flash_trainable_seg(q, k, v, segment_ids, causal, window)
            return _flash_trainable(q, k, v, causal, window)
    return attention_reference(
        q, repeat_kv(k, n_rep), repeat_kv(v, n_rep),
        causal=causal, segment_ids=segment_ids, window=window,
    )
