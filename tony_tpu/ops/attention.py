"""Attention kernels: XLA reference + Pallas TPU flash attention.

The compute hot path the reference never owned (it lived inside TF/torch —
SURVEY.md §2.4): here multi-head attention is a first-class op with
- ``attention_reference``: einsum+softmax through XLA (runs everywhere; XLA
  already fuses mask+softmax into the matmuls well on TPU),
- ``flash_attention``: blockwise-online-softmax Pallas kernel keeping the
  score matrix in VMEM tiles (O(T) memory), for long sequences on TPU,
- ``mha``: the dispatcher models call (impl='auto' picks per backend).

GQA/MQA is handled by broadcasting KV heads before the kernel.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp

NEG_INF = -1e30


def repeat_kv(k: jax.Array, n_rep: int) -> jax.Array:
    """[B, Hkv, T, D] → [B, Hkv*n_rep, T, D] (GQA head broadcast)."""
    if n_rep == 1:
        return k
    B, H, T, D = k.shape
    return jnp.broadcast_to(k[:, :, None], (B, H, n_rep, T, D)).reshape(B, H * n_rep, T, D)


def attention_reference(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    *,
    causal: bool = True,
    scale: float | None = None,
) -> jax.Array:
    """Plain attention; q/k/v: [B, H, T, D] (KV already head-broadcast)."""
    D = q.shape[-1]
    scale = scale if scale is not None else D ** -0.5
    s = jnp.einsum("bhqd,bhkd->bhqk", q, k, preferred_element_type=jnp.float32) * scale
    if causal:
        Tq, Tk = s.shape[-2], s.shape[-1]
        mask = jnp.tril(jnp.ones((Tq, Tk), dtype=bool), Tk - Tq)
        s = jnp.where(mask, s, NEG_INF)
    p = jax.nn.softmax(s, axis=-1)
    return jnp.einsum("bhqk,bhkd->bhqd", p.astype(v.dtype), v)


# ---------------------------------------------------------------------------
# Pallas flash attention (TPU)
# ---------------------------------------------------------------------------

def _flash_kernel(q_ref, k_ref, v_ref, o_ref, *, block_k: int, causal: bool, scale: float):
    """Grid: (B*H, Tq//block_q). Online softmax over KV blocks in VMEM."""
    from jax.experimental import pallas as pl

    block_q, D = q_ref.shape
    Tk = k_ref.shape[0]
    q_blk_idx = pl.program_id(1)
    q = q_ref[:] .astype(jnp.float32) * scale
    q_pos = q_blk_idx * block_q + jax.lax.broadcasted_iota(jnp.int32, (block_q, 1), 0)

    num_k_blocks = pl.cdiv(Tk, block_k)
    if causal:
        # only blocks at or below the diagonal contribute
        num_k_blocks = jnp.minimum(num_k_blocks, (q_blk_idx + 1) * block_q // block_k + 1)

    def body(kb, carry):
        o, m, l = carry
        k_blk = k_ref[pl.ds(kb * block_k, block_k), :].astype(jnp.float32)
        v_blk = v_ref[pl.ds(kb * block_k, block_k), :].astype(jnp.float32)
        s = jax.lax.dot_general(
            q, k_blk, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        )  # [block_q, block_k]
        if causal:
            k_pos = kb * block_k + jax.lax.broadcasted_iota(jnp.int32, (1, block_k), 1)
            s = jnp.where(q_pos >= k_pos, s, NEG_INF)
        m_b = jnp.max(s, axis=-1, keepdims=True)
        m_new = jnp.maximum(m, m_b)
        alpha = jnp.exp(m - m_new)
        p = jnp.exp(s - m_new)
        l_new = l * alpha + jnp.sum(p, axis=-1, keepdims=True)
        o_new = o * alpha + jax.lax.dot_general(
            p, v_blk, (((1,), (0,)), ((), ())), preferred_element_type=jnp.float32
        )
        return o_new, m_new, l_new

    o0 = jnp.zeros((block_q, D), jnp.float32)
    m0 = jnp.full((block_q, 1), NEG_INF, jnp.float32)
    l0 = jnp.zeros((block_q, 1), jnp.float32)
    o, m, l = jax.lax.fori_loop(0, num_k_blocks, body, (o0, m0, l0))
    o_ref[:] = (o / jnp.maximum(l, 1e-20)).astype(o_ref.dtype)


@functools.partial(jax.jit, static_argnames=("causal", "block_q", "block_k"))
def flash_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    *,
    causal: bool = True,
    block_q: int = 256,
    block_k: int = 256,
) -> jax.Array:
    """Pallas TPU flash attention; q/k/v: [B, H, T, D], T % block == 0."""
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    B, H, Tq, D = q.shape
    Tk = k.shape[2]
    block_q = min(block_q, Tq)
    block_k = min(block_k, Tk)
    scale = D ** -0.5
    if Tq % block_q or Tk % block_k:
        return attention_reference(q, k, v, causal=causal)

    qf = q.reshape(B * H, Tq, D)
    kf = k.reshape(B * H, Tk, D)
    vf = v.reshape(B * H, Tk, D)

    kernel = functools.partial(_flash_kernel, block_k=block_k, causal=causal, scale=scale)
    out = pl.pallas_call(
        kernel,
        grid=(B * H, Tq // block_q),
        in_specs=[
            pl.BlockSpec((None, block_q, D), lambda b, i: (b, i, 0)),
            pl.BlockSpec((None, Tk, D), lambda b, i: (b, 0, 0)),
            pl.BlockSpec((None, Tk, D), lambda b, i: (b, 0, 0)),
        ],
        out_specs=pl.BlockSpec((None, block_q, D), lambda b, i: (b, i, 0)),
        out_shape=jax.ShapeDtypeStruct((B * H, Tq, D), q.dtype),
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("parallel", "arbitrary"),
        ),
        cost_estimate=pl.CostEstimate(
            flops=4 * B * H * Tq * Tk * D,
            bytes_accessed=2 * (qf.size + kf.size + vf.size) * q.dtype.itemsize,
            transcendentals=B * H * Tq * Tk,
        ),
    )(qf, kf, vf)
    return out.reshape(B, H, Tq, D)


# -- trainable flash attention: pallas forward + custom VJP ------------------
# pallas_call has no JVP rule (pallas guide §20: production kernels define a
# custom VJP). v1 backward recomputes through the XLA reference path — the
# forward stays O(T) memory in the kernel; a Pallas backward kernel is the
# follow-up optimization for long sequences.

@functools.partial(jax.custom_vjp, nondiff_argnums=(3,))
def _flash_trainable(q, k, v, causal):
    return flash_attention(q, k, v, causal=causal)


def _flash_fwd(q, k, v, causal):
    return flash_attention(q, k, v, causal=causal), (q, k, v)


def _flash_bwd(causal, res, g):
    q, k, v = res
    _, vjp = jax.vjp(lambda q, k, v: attention_reference(q, k, v, causal=causal), q, k, v)
    return vjp(g)


_flash_trainable.defvjp(_flash_fwd, _flash_bwd)


def mha(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    *,
    causal: bool = True,
    impl: str = "auto",
) -> jax.Array:
    """Dispatcher: Pallas flash kernel on TPU, XLA reference elsewhere."""
    if impl == "auto":
        impl = "flash" if jax.default_backend() not in ("cpu",) else "reference"
    if impl == "flash":
        Tq, Tk = q.shape[2], k.shape[2]
        if Tq % min(256, Tq) == 0 and Tk % min(256, Tk) == 0 and Tq >= 128:
            return _flash_trainable(q, k, v, causal)
    return attention_reference(q, k, v, causal=causal)
