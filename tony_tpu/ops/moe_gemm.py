"""Fused grouped-GEMM SwiGLU kernel for MoE expert compute (Pallas TPU).

The XLA path (``jax.lax.ragged_dot``) runs the three expert GEMMs as
separate megablox custom calls with the [N, F] gate/up activations making
full HBM round-trips between them, and loses ~40% throughput to multi-group
handling even on 512-aligned uniform groups (measured, BASELINE.md r3).
This kernel computes the whole expert MLP — ``silu(x·Wg) ⊙ (x·Wu) · Wd`` —
in ONE VMEM pass per row tile:

- rows arrive sorted by expert (parallel/expert.route_ragged) with group
  sizes padded to the row-tile size, so every tile belongs to exactly one
  expert; a scalar-prefetched ``tile_group`` map drives the weight
  BlockSpecs, and consecutive tiles of the same expert keep the weight
  slab resident in VMEM (Pallas revisit caching);
- the [tile, F] gate/up intermediates live and die in VMEM — no HBM
  round-trips between the three GEMMs;
- the backward is one fused kernel too: recomputes gate/up per tile, then
  produces dx per tile and accumulates dWg/dWu/dWd in VMEM f32 across each
  expert's run of tiles, flushing once per expert (revisited out blocks).

No counterpart in the reference (its MoE support is framework-side; the
equivalent fused kernels live in vendor libraries). VMEM is dominated by
the per-expert weight slabs (3·D·F bf16 ≈ 12.6 MB at D=1024/F=2048,
double-buffered by the pipeline) plus, in the backward, the f32 dW
accumulators (3·D·F·4 ≈ 25 MB); the row-tile buffers scale with TILE_M
(~0.5 MB at the default 128). Measured fine on a v5e's 128 MB at tiles
64–512.
"""

from __future__ import annotations

import functools
import os

import jax
import jax.numpy as jnp
import numpy as np

from tony_tpu.compat import tpu_compiler_params

_INTERPRET = os.environ.get("TONY_PALLAS_INTERPRET", "") == "1"

# fwd row-tile; group sizes are padded to multiples of this. 128 is the r3
# measured optimum on v5e at the bench geometry (same-session ladder:
# 64→36.2%, 96→38.1%, 128→38.4%, 256→36.9%, 512→36.8% active MFU — less
# group-padding waste and tighter pipelining beat bigger GEMM tiles).
# Env-overridable for per-hardware tuning; BASELINE.md records the ladder.
TILE_M = int(os.environ.get("TONY_MOE_TILE", "128"))
# bwd row-tile (more VMEM-hungry: f32 dW accumulators); must divide TILE_M
# when smaller (the backward splits fwd tiles into bwd tiles)
TILE_M_BWD = int(os.environ.get("TONY_MOE_TILE_BWD", "128"))
# fwd F-chunking: >0 splits the expert MLP's hidden dim into chunks of this
# size — per chunk: gate/up GEMMs, the silu·mul on the VPU, and a chunked
# down-GEMM accumulating [tile, D] in f32. The monolithic kernel serializes
# MXU(g) → MXU(u) → VPU(h) → MXU(down) per tile; the chunked form lets
# Mosaic overlap the next chunk's MXU work with the current chunk's VPU
# tail. r4 same-session ladder (active MFU, 2 reps): 0 → 38.18/37.92,
# 512 → 38.25/38.25, 1024 → 38.13/38.09 — 512 never loses, ships as
# default; shapes where F % F_CHUNK != 0 fall back to monolithic.
F_CHUNK = int(os.environ.get("TONY_MOE_FCHUNK", "512"))
if F_CHUNK and (F_CHUNK < 128 or F_CHUNK % 128):
    raise ValueError(f"TONY_MOE_FCHUNK={F_CHUNK}: must be 0 or a multiple of 128 >= 128")

# fail at import, not deep inside Mosaic lowering or the first backward
for _name, _t in (("TONY_MOE_TILE", TILE_M), ("TONY_MOE_TILE_BWD", TILE_M_BWD)):
    if _t < 8 or _t % 8:
        raise ValueError(f"{_name}={_t}: row tiles must be positive multiples of 8")
if TILE_M > TILE_M_BWD and TILE_M % TILE_M_BWD:
    raise ValueError(
        f"TONY_MOE_TILE={TILE_M} is larger than but not a multiple of "
        f"TONY_MOE_TILE_BWD={TILE_M_BWD}: the backward cannot split the "
        "padded group spans — pick a multiple (or set them equal)"
    )
# NOTE: TILE_M_BWD > TILE_M is legal — it simply never applies for calls at
# the default fwd tile (the backward only SPLITS fwd tiles), but a caller
# passing an explicitly larger ``tile=`` still gets the coarser bwd split.


def tuned_tile(E: int, D: int, F: int, dtype) -> int:
    """``TILE_M``, overridden by an ops/tune.py cache hit for this expert
    geometry on this device. Validated against the row-tile preconditions
    (positive multiple of 8; splittable by TILE_M_BWD when larger) so a
    stale cache entry degrades to the default instead of failing lowering.
    Callers pick the tile ONCE per MoE layer call (parallel/expert.py) —
    it also sets the routing's group padding, so it must be chosen before
    route_ragged, not inside the kernel."""
    if "TONY_MOE_TILE" in os.environ:
        # an EXPLICIT env override is the operator's debugging lever — it
        # must beat the tune cache (which otherwise wins silently)
        return TILE_M
    from tony_tpu.ops import tune

    params = tune.lookup("moe_gemm", (E, D, F), str(dtype))
    t = int(params.get("tile", 0)) if params else 0
    if t < 8 or t % 8 or (t > TILE_M_BWD and t % TILE_M_BWD):
        return TILE_M
    return t


def _silu(x):
    return x * jax.nn.sigmoid(x)


def _fwd_kernel(tg_ref, xs_ref, wg_ref, wu_ref, wd_ref, ys_ref):
    x = xs_ref[...]
    F = wg_ref.shape[2]
    if F_CHUNK and F % F_CHUNK == 0 and F > F_CHUNK:
        # F-chunked: overlap the next chunk's gate/up MXU work with the
        # current chunk's VPU silu·mul tail (statically unrolled so Mosaic
        # can software-pipeline the chunk sequence)
        acc = jnp.zeros((x.shape[0], wd_ref.shape[2]), jnp.float32)
        for c in range(F // F_CHUNK):
            sl = slice(c * F_CHUNK, (c + 1) * F_CHUNK)
            g = jnp.dot(x, wg_ref[0, :, sl], preferred_element_type=jnp.float32)
            u = jnp.dot(x, wu_ref[0, :, sl], preferred_element_type=jnp.float32)
            h = (_silu(g) * u).astype(x.dtype)
            acc += jnp.dot(h, wd_ref[0, sl, :], preferred_element_type=jnp.float32)
        ys_ref[...] = acc.astype(ys_ref.dtype)
        return
    g = jnp.dot(x, wg_ref[0], preferred_element_type=jnp.float32)
    u = jnp.dot(x, wu_ref[0], preferred_element_type=jnp.float32)
    h = (_silu(g) * u).astype(x.dtype)
    ys_ref[...] = jnp.dot(h, wd_ref[0], preferred_element_type=jnp.float32).astype(
        ys_ref.dtype
    )


def _bwd_kernel(
    tg_ref, xs_ref, dy_ref, wg_ref, wu_ref, wd_ref,
    dxs_ref, dwg_ref, dwu_ref, dwd_ref,
):
    from jax.experimental import pallas as pl

    m = pl.program_id(0)
    prev = tg_ref[jnp.maximum(m - 1, 0)]
    first_of_group = jnp.logical_or(m == 0, tg_ref[m] != prev)

    @pl.when(first_of_group)
    def _init():
        dwg_ref[...] = jnp.zeros(dwg_ref.shape, dwg_ref.dtype)
        dwu_ref[...] = jnp.zeros(dwu_ref.shape, dwu_ref.dtype)
        dwd_ref[...] = jnp.zeros(dwd_ref.shape, dwd_ref.dtype)

    x = xs_ref[...]
    dy = dy_ref[...]
    # recompute the forward intermediates for this tile (remat-in-kernel)
    g = jnp.dot(x, wg_ref[0], preferred_element_type=jnp.float32)
    u = jnp.dot(x, wu_ref[0], preferred_element_type=jnp.float32)
    s = jax.nn.sigmoid(g)
    silu_g = g * s
    h = (silu_g * u).astype(x.dtype)

    # dh = dy · Wd^T  (contract the D dims — no transposed weight copy)
    dh = jax.lax.dot_general(
        dy, wd_ref[0], (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
    )
    du = (dh * silu_g).astype(x.dtype)
    dsilu = s * (1.0 + g * (1.0 - s))
    dg = (dh * u * dsilu).astype(x.dtype)

    dxs = jax.lax.dot_general(
        dg, wg_ref[0], (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
    ) + jax.lax.dot_general(
        du, wu_ref[0], (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
    )
    dxs_ref[...] = dxs.astype(dxs_ref.dtype)

    # per-expert weight grads: accumulate f32 in VMEM across the expert's
    # tile run (the out blocks revisit while tile_group stays constant)
    dwg_ref[0] += jax.lax.dot_general(
        x, dg, (((0,), (0,)), ((), ())), preferred_element_type=jnp.float32
    )
    dwu_ref[0] += jax.lax.dot_general(
        x, du, (((0,), (0,)), ((), ())), preferred_element_type=jnp.float32
    )
    dwd_ref[0] += jax.lax.dot_general(
        h, dy, (((0,), (0,)), ((), ())), preferred_element_type=jnp.float32
    )


def _fwd_call(xs, wg, wu, wd, tile_group, tile):
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    PN, D = xs.shape
    E, _, F = wg.shape
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1,
        grid=(PN // tile,),
        in_specs=[
            pl.BlockSpec((tile, D), lambda m, tg: (m, 0)),
            pl.BlockSpec((1, D, F), lambda m, tg: (tg[m], 0, 0)),
            pl.BlockSpec((1, D, F), lambda m, tg: (tg[m], 0, 0)),
            pl.BlockSpec((1, F, D), lambda m, tg: (tg[m], 0, 0)),
        ],
        out_specs=pl.BlockSpec((tile, D), lambda m, tg: (m, 0)),
    )
    return pl.pallas_call(
        _fwd_kernel,
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((PN, D), xs.dtype),
        compiler_params=tpu_compiler_params(
            dimension_semantics=("arbitrary",),  # revisit caching needs order
            vmem_limit_bytes=100 * 1024 * 1024,  # weight slabs resident (v5e: 128M)
        ),
        interpret=_INTERPRET,
        cost_estimate=pl.CostEstimate(
            flops=2 * PN * D * F * 3,
            bytes_accessed=(xs.size * 2 + (wg.size + wu.size + wd.size)) * xs.dtype.itemsize,
            transcendentals=PN * F,
        ),
    )(tile_group, xs, wg, wu, wd)


def _bwd_call(xs, dy, wg, wu, wd, tile_group, tile):
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    PN, D = xs.shape
    E, _, F = wg.shape
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1,
        grid=(PN // tile,),
        in_specs=[
            pl.BlockSpec((tile, D), lambda m, tg: (m, 0)),
            pl.BlockSpec((tile, D), lambda m, tg: (m, 0)),
            pl.BlockSpec((1, D, F), lambda m, tg: (tg[m], 0, 0)),
            pl.BlockSpec((1, D, F), lambda m, tg: (tg[m], 0, 0)),
            pl.BlockSpec((1, F, D), lambda m, tg: (tg[m], 0, 0)),
        ],
        out_specs=[
            pl.BlockSpec((tile, D), lambda m, tg: (m, 0)),
            pl.BlockSpec((1, D, F), lambda m, tg: (tg[m], 0, 0)),
            pl.BlockSpec((1, D, F), lambda m, tg: (tg[m], 0, 0)),
            pl.BlockSpec((1, F, D), lambda m, tg: (tg[m], 0, 0)),
        ],
    )
    return pl.pallas_call(
        _bwd_kernel,
        grid_spec=grid_spec,
        out_shape=[
            jax.ShapeDtypeStruct((PN, D), xs.dtype),
            jax.ShapeDtypeStruct((E, D, F), jnp.float32),
            jax.ShapeDtypeStruct((E, D, F), jnp.float32),
            jax.ShapeDtypeStruct((E, F, D), jnp.float32),
        ],
        compiler_params=tpu_compiler_params(
            dimension_semantics=("arbitrary",),
            vmem_limit_bytes=100 * 1024 * 1024,  # f32 dW accumulators + weight slabs
        ),
        interpret=_INTERPRET,
        cost_estimate=pl.CostEstimate(
            flops=2 * PN * D * F * 8,
            bytes_accessed=(xs.size * 3 + 2 * (wg.size + wu.size + wd.size))
            * xs.dtype.itemsize,
            transcendentals=PN * F,
        ),
    )(tile_group, xs, dy, wg, wu, wd)


@functools.partial(jax.custom_vjp, nondiff_argnums=(5,))
def moe_swiglu_grouped(xs, wg, wu, wd, tile_group, tile=TILE_M):
    """Fused grouped SwiGLU: ``ys[i] = silu(xs[i]·Wg[g]) ⊙ (xs[i]·Wu[g]) · Wd[g]``
    where ``g = tile_group[i // tile]``.

    xs: [PN, D] rows sorted by expert, each group's span padded to a
    multiple of ``tile`` (see parallel/expert.route_ragged with tile=...);
    wg/wu: [E, D, F]; wd: [E, F, D]; tile_group: [PN/tile] int32 expert id
    per row tile (must be non-decreasing — weight residency and the
    backward's accumulate-then-flush both rely on it).

    Rows inside a group's padding compute garbage through the expert — the
    caller must never read them (the choice-order combine gathers only real
    rows) and their upstream cotangent must be zero (it is: the combine's
    transpose scatter-adds only real rows).
    """
    return _fwd_call(xs, wg, wu, wd, tile_group, tile)


def _vjp_fwd(xs, wg, wu, wd, tile_group, tile):
    from jax.ad_checkpoint import checkpoint_name

    ys = _fwd_call(xs, wg, wu, wd, tile_group, tile)
    ys = checkpoint_name(ys, "moe_gemm")
    return ys, (xs, wg, wu, wd, tile_group)


def _vjp_bwd(tile, res, dy):
    xs, wg, wu, wd, tile_group = res
    bwd_tile = tile
    if tile > TILE_M_BWD:
        if tile % TILE_M_BWD:  # import checks cover defaults; tile is a call arg
            raise ValueError(
                f"tile={tile} is larger than but not a multiple of "
                f"TONY_MOE_TILE_BWD={TILE_M_BWD}: the backward cannot split "
                "the padded group spans — pick a multiple (or set them equal)"
            )
        # finer backward tiling: same group spans (TILE_M_BWD divides the
        # fwd tile), each fwd tile simply splits into tile/TILE_M_BWD rows
        tile_group = jnp.repeat(tile_group, tile // TILE_M_BWD)
        bwd_tile = TILE_M_BWD
    dxs, dwg, dwu, dwd = _bwd_call(
        xs, dy.astype(xs.dtype), wg, wu, wd, tile_group, bwd_tile
    )
    return (
        dxs,
        dwg.astype(wg.dtype),
        dwu.astype(wu.dtype),
        dwd.astype(wd.dtype),
        np.zeros(tile_group.shape, jax.dtypes.float0),
    )


moe_swiglu_grouped.defvjp(_vjp_fwd, _vjp_bwd)


def tile_group_map(group_sizes_padded: jax.Array, num_tiles: int, tile: int) -> jax.Array:
    """[E] padded group sizes → [num_tiles] expert id per row tile.

    Tiles beyond ``sum(group_sizes_padded)`` clamp to the last expert —
    they compute garbage on pad rows that nothing reads, and contribute
    zero to every gradient (their upstream cotangent rows are zero).
    """
    bounds = jnp.cumsum(group_sizes_padded)                       # [E]
    starts = jnp.arange(num_tiles, dtype=jnp.int32) * tile
    return jnp.minimum(
        jnp.searchsorted(bounds, starts, side="right").astype(jnp.int32),
        group_sizes_padded.shape[0] - 1,
    )
