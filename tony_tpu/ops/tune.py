"""Pallas kernel autotuner: measured block sizes per (device kind, shape, dtype).

The hot kernels (flash attention fwd/bwd in ops/attention.py, the fused MoE
grouped GEMM in ops/moe_gemm.py, the int8 matmul in ops/quant.py) ship with
block sizes measured ONCE on one device generation (the r3 v5e ladder,
BASELINE.md) and frozen as module constants. Those constants are the right
cold-cache default, but they are not the optimum for every (shape, dtype,
device) the framework meets — a different chip generation, head dim, or
sequence length can move the best block by 2+ MFU points, and until now the
only recourse was the ``TONY_FLASH_BQ``-style env overrides, global to the
whole process.

This module closes the loop:

- ``tony tune`` (cli/tune.py) sweeps each kernel's candidate block sizes on
  the REAL backend for the shapes a preset/model will run, wall-timing each
  candidate, and persists the winners to an on-disk JSON cache keyed by
  ``(op, device_kind, shape, dtype)``;
- the kernel entry points consult the cache at trace time via
  :func:`lookup` — a cache hit overrides the module-constant default, a miss
  (or ``TONY_TUNE_DISABLE=1``) keeps today's behavior byte-for-byte.

The cache file defaults to ``~/.cache/tony-tpu/tune.json`` and is overridden
by ``TONY_TUNE_CACHE`` (the executor exports it from ``tony.tune.cache-file``
so tuned jobs see the same cache on every worker). Lookups happen at trace
time only — once per compiled shape, never on the step path.
"""

from __future__ import annotations

import json
import os
import time
from typing import Any, Callable, Iterable

from tony_tpu import constants

ENV_CACHE = constants.ENV_TUNE_CACHE      # cache file override (tony.tune.cache-file)
ENV_DISABLE = constants.ENV_TUNE_DISABLE  # "1" → kernels ignore the cache entirely


def default_cache_path() -> str:
    """``$TONY_TUNE_CACHE`` when set, else the per-user cache location."""
    return os.environ.get(ENV_CACHE) or os.path.join(
        os.path.expanduser("~"), ".cache", "tony-tpu", "tune.json"
    )


def device_kind() -> str:
    """The backend's device kind (cache-key component); 'cpu' offline."""
    try:
        import jax

        return str(getattr(jax.devices()[0], "device_kind", jax.default_backend()))
    except Exception:  # noqa: BLE001 — no backend is a valid tuning-off state
        return "unknown"


def cache_key(op: str, kind: str, shape: Iterable[int], dtype: Any) -> str:
    return "|".join([op, kind, "x".join(str(int(d)) for d in shape), str(dtype)])


class TuneCache:
    """One JSON file of tuned winners: ``{key: {"params": {...}, "ms": f,
    "tuned_at": iso}}``. Reads are mtime-aware (a re-tune is picked up
    without a restart of THIS object); writes merge with the on-disk state
    so two concurrent tuners don't clobber each other's ops."""

    def __init__(self, path: str | None = None):
        self.path = path or default_cache_path()
        self._disk: dict[str, dict] = {}      # mirror of the file, mtime-tracked
        self._local: dict[str, dict] = {}     # puts not yet saved (win over disk)
        self._mtime: float | None = None

    def _refresh(self) -> None:
        try:
            mtime = os.stat(self.path).st_mtime_ns
        except OSError:
            self._disk, self._mtime = {}, None
            return
        if mtime == self._mtime:
            return
        try:
            with open(self.path, encoding="utf-8") as f:
                data = json.load(f)
            entries = data.get("entries", {})
            self._disk = entries if isinstance(entries, dict) else {}
            self._mtime = mtime
        except (OSError, ValueError):
            # a torn/corrupt cache must never break a kernel call: treat as
            # cold and let the next save rewrite it whole
            self._disk, self._mtime = {}, None

    def get(
        self, op: str, shape: Iterable[int], dtype: Any, kind: str | None = None
    ) -> dict[str, int] | None:
        """Tuned params for one kernel call site, or None (cold cache)."""
        self._refresh()
        key = cache_key(op, kind or device_kind(), shape, dtype)
        entry = self._local.get(key) or self._disk.get(key)
        params = entry.get("params") if isinstance(entry, dict) else None
        if not isinstance(params, dict):
            return None
        try:
            return {str(k): int(v) for k, v in params.items()}
        except (TypeError, ValueError):
            return None

    def put(
        self, op: str, shape: Iterable[int], dtype: Any, params: dict[str, int],
        ms: float | None = None, kind: str | None = None,
    ) -> None:
        self._local[cache_key(op, kind or device_kind(), shape, dtype)] = {
            "params": {str(k): int(v) for k, v in params.items()},
            **({"ms": round(float(ms), 3)} if ms is not None else {}),
            "tuned_at": time.strftime("%Y-%m-%dT%H:%M:%S"),
        }

    def save(self) -> str:
        """Atomic write (merged with any entries another process landed
        since our last refresh); returns the path written."""
        self._mtime = None
        self._refresh()
        merged = {**self._disk, **self._local}
        os.makedirs(os.path.dirname(self.path) or ".", exist_ok=True)
        tmp = self.path + ".tmp"
        with open(tmp, "w", encoding="utf-8") as f:
            json.dump({"version": 1, "entries": merged}, f, indent=1, sort_keys=True)
            f.write("\n")
        os.replace(tmp, self.path)
        self._disk, self._local, self._mtime = merged, {}, None
        return self.path


_shared: TuneCache | None = None


def shared_cache() -> TuneCache:
    """Process-wide cache instance bound to the CURRENT env-resolved path
    (re-bound when TONY_TUNE_CACHE changes, so tests can redirect it)."""
    global _shared
    path = default_cache_path()
    if _shared is None or _shared.path != path:
        _shared = TuneCache(path)
    return _shared


def lookup(op: str, shape: Iterable[int], dtype: Any) -> dict[str, int] | None:
    """The kernel entry points' cache consult: tuned params or None.

    Trace-time only (static block sizes); disabled by ``TONY_TUNE_DISABLE=1``
    and inert (one env read + a failed stat) when nothing was ever tuned.
    """
    if os.environ.get(ENV_DISABLE) == "1":
        return None
    return shared_cache().get(op, shape, dtype)


# ---------------------------------------------------------------------------
# Sweep machinery — `tony tune` drives these on a real backend.
# ---------------------------------------------------------------------------

def measure(thunk: Callable[[], Any], steps: int = 3, warmup: int = 1) -> float:
    """Median wall-time (ms) of ``thunk`` over ``steps`` timed runs, each
    synced via block_until_ready, after ``warmup`` compile runs."""
    import jax

    for _ in range(max(warmup, 1)):
        jax.block_until_ready(thunk())
    times = []
    for _ in range(max(steps, 1)):
        t0 = time.perf_counter()
        jax.block_until_ready(thunk())  # lint: disable=host-sync — per-run sync IS the measurement
        times.append((time.perf_counter() - t0) * 1000.0)
    times.sort()
    return times[len(times) // 2]


def flash_candidates(Tq: int, Tk: int) -> list[tuple[int, int]]:
    """(block_q, block_k) grid: alignment-legal blocks that divide the
    sequence lengths, the kernels' lowering preconditions (attention.py
    routes anything else to the XLA reference path)."""
    out = []
    for bq in (128, 256, 512):
        if bq > Tq or Tq % bq:
            continue
        for bk in (128, 256, 512, 1024):
            if bk > Tk or Tk % bk:
                continue
            out.append((bq, bk))
    return out


def sweep_flash(
    B: int, H: int, Hkv: int, T: int, D: int, dtype: str = "bfloat16",
    causal: bool = True, steps: int = 3,
) -> list[dict]:
    """Sweep flash fwd and bwd block sizes for one attention geometry;
    returns result rows (op/params/ms, best first per op) WITHOUT writing
    the cache — the CLI decides what to persist."""
    import jax
    import jax.numpy as jnp

    from tony_tpu.ops import attention as A

    dt = jnp.dtype(dtype)
    ks = [jax.random.fold_in(jax.random.PRNGKey(0), i) for i in range(4)]
    q = (jax.random.normal(ks[0], (B, H, T, D)) * 0.5).astype(dt)
    k = (jax.random.normal(ks[1], (B, Hkv, T, D)) * 0.5).astype(dt)
    v = (jax.random.normal(ks[2], (B, Hkv, T, D)) * 0.5).astype(dt)
    do = (jax.random.normal(ks[3], (B, H, T, D)) * 0.5).astype(dt)
    shape = (B, H, Hkv, T, T, D)

    rows: list[dict] = []
    fwd_rows: list[dict] = []
    for bq, bk in flash_candidates(T, T):
        fwd = jax.jit(
            lambda q, k, v, bq=bq, bk=bk: A._flash_fwd_lanes(q, k, v, causal, bq, bk)
        )
        try:
            ms = measure(lambda: fwd(q, k, v), steps=steps)
        except Exception as e:  # noqa: BLE001 — a non-lowering candidate just loses
            rows.append({"op": "flash_fwd", "shape": shape, "dtype": str(dt),
                         "params": {"block_q": bq, "block_k": bk},
                         "ms": None, "error": f"{type(e).__name__}: {e}"})
            continue
        fwd_rows.append({"op": "flash_fwd", "shape": shape, "dtype": str(dt),
                         "params": {"block_q": bq, "block_k": bk}, "ms": ms})
    o, lse = None, None
    if fwd_rows:
        best_fwd = min(fwd_rows, key=lambda r: r["ms"])
        p = best_fwd["params"]
        o, lse = A._flash_fwd_lanes(q, k, v, causal, p["block_q"], p["block_k"])

    bwd_rows: list[dict] = []
    if o is not None:
        for bq, bk in flash_candidates(T, T):
            bwd = jax.jit(
                lambda q, k, v, o, lse, do, bq=bq, bk=bk:
                A._flash_bwd_impl(q, k, v, o, lse, do, causal, bq, bk)
            )
            try:
                ms = measure(lambda: bwd(q, k, v, o, lse, do), steps=steps)
            except Exception as e:  # noqa: BLE001
                rows.append({"op": "flash_bwd", "shape": shape, "dtype": str(dt),
                             "params": {"block_q": bq, "block_k": bk},
                             "ms": None, "error": f"{type(e).__name__}: {e}"})
                continue
            bwd_rows.append({"op": "flash_bwd", "shape": shape, "dtype": str(dt),
                             "params": {"block_q": bq, "block_k": bk}, "ms": ms})
    return (sorted(fwd_rows, key=lambda r: r["ms"])
            + sorted(bwd_rows, key=lambda r: r["ms"]) + rows)


def moe_candidates(N: int) -> list[int]:
    return [t for t in (64, 128, 256, 512) if t <= max(N, 64)]


def sweep_moe(
    E: int, D: int, F: int, N: int, dtype: str = "bfloat16", steps: int = 3,
) -> list[dict]:
    """Sweep the fused MoE grouped-GEMM row tile for one expert geometry
    (fwd+bwd together — the tile is shared, TILE_M_BWD must divide it)."""
    import jax
    import jax.numpy as jnp

    from tony_tpu.ops import moe_gemm

    dt = jnp.dtype(dtype)
    ks = jax.random.split(jax.random.PRNGKey(1), 4)
    wg = (jax.random.normal(ks[0], (E, D, F)) / D ** 0.5).astype(dt)
    wu = (jax.random.normal(ks[1], (E, D, F)) / D ** 0.5).astype(dt)
    wd = (jax.random.normal(ks[2], (E, F, D)) / F ** 0.5).astype(dt)
    shape = (E, D, F)

    rows: list[dict] = []
    for tile in moe_candidates(N):
        per = -(-max(N // E, 1) // tile) * tile       # equal groups, tile-padded
        PN = per * E
        xs = (jax.random.normal(ks[3], (PN, D)) * 0.5).astype(dt)
        group_sizes = jnp.full((E,), per, jnp.int32)
        tg = moe_gemm.tile_group_map(group_sizes, PN // tile, tile)

        def loss(xs, wg, wu, wd, tg=tg, tile=tile):
            y = moe_gemm.moe_swiglu_grouped(xs, wg, wu, wd, tg, tile)
            return (y.astype(jnp.float32) ** 2).sum()

        step = jax.jit(jax.value_and_grad(loss, argnums=(0, 1, 2, 3)))
        try:
            ms = measure(lambda: step(xs, wg, wu, wd), steps=steps)
        except Exception as e:  # noqa: BLE001
            rows.append({"op": "moe_gemm", "shape": shape, "dtype": str(dt),
                         "params": {"tile": tile}, "ms": None,
                         "error": f"{type(e).__name__}: {e}"})
            continue
        rows.append({"op": "moe_gemm", "shape": shape, "dtype": str(dt),
                     "params": {"tile": tile}, "ms": ms})
    ok = [r for r in rows if r["ms"] is not None]
    bad = [r for r in rows if r["ms"] is None]
    return sorted(ok, key=lambda r: r["ms"]) + bad


def int8_candidates(M: int, K: int, N: int) -> list[tuple[int, int, int]]:
    out = []
    for bm in (128, 256, 512):
        for bn in (128, 256, 512):
            for bk in (256, 512, 1024):
                if bm <= M and bn <= N and bk <= K and not (M % bm or N % bn or K % bk):
                    out.append((bm, bn, bk))
    return out


def sweep_int8(
    M: int, K: int, N: int, dtype: str = "bfloat16", steps: int = 3,
) -> list[dict]:
    """Sweep the int8 weight-matmul block sizes for one GEMM geometry."""
    import jax
    import jax.numpy as jnp

    from tony_tpu.ops import quant

    dt = jnp.dtype(dtype)
    key = jax.random.PRNGKey(2)
    x = jax.random.normal(key, (M, K)).astype(dt)
    qt = quant.quantize_int8(jax.random.normal(jax.random.fold_in(key, 1), (K, N)))
    shape = (M, K, N)

    rows: list[dict] = []
    for bm, bn, bk in int8_candidates(M, K, N):
        try:
            ms = measure(
                lambda: quant.int8_matmul(x, qt, block_m=bm, block_n=bn, block_k=bk),
                steps=steps,
            )
        except Exception as e:  # noqa: BLE001
            rows.append({"op": "int8_matmul", "shape": shape, "dtype": str(dt),
                         "params": {"block_m": bm, "block_n": bn, "block_k": bk},
                         "ms": None, "error": f"{type(e).__name__}: {e}"})
            continue
        rows.append({"op": "int8_matmul", "shape": shape, "dtype": str(dt),
                     "params": {"block_m": bm, "block_n": bn, "block_k": bk},
                     "ms": ms})
    ok = [r for r in rows if r["ms"] is not None]
    bad = [r for r in rows if r["ms"] is None]
    return sorted(ok, key=lambda r: r["ms"]) + bad


def persist_winners(rows: list[dict], cache: TuneCache | None = None) -> TuneCache:
    """Store the best (lowest-ms) row per (op, shape, dtype) into the cache
    and save it. Rows without a measurement (lowering failures) never win."""
    cache = cache or shared_cache()
    best: dict[tuple, dict] = {}
    for r in rows:
        if r.get("ms") is None:
            continue
        k = (r["op"], tuple(r["shape"]), r["dtype"])
        if k not in best or r["ms"] < best[k]["ms"]:
            best[k] = r
    for (op, shape, dtype), r in best.items():
        cache.put(op, shape, dtype, r["params"], ms=r["ms"])
    cache.save()
    return cache
