"""Replicated serving control plane (docs/serving.md, "Replicated serving").

``tony serve --replicas N`` turns the single AM-supervised inference task
into a fault-tolerant fleet: N ``serve`` replicas under the ordinary gang
machinery, fronted by three submitter-side pieces —

- :class:`~tony_tpu.serve.router.FleetRouter`: HTTP front door with
  least-outstanding balancing, health-checked failover/retry, and optional
  tail hedging;
- :class:`~tony_tpu.serve.health.HealthMonitor`: AM-registry endpoint
  discovery (re-resolves across gang restarts) + active/passive per-replica
  health (healthy → draining → down);
- :class:`~tony_tpu.serve.autoscaler.Autoscaler`: queue-depth /
  slot-utilization driven replica retargeting through the AM's
  ``resize_jobtype`` elastic-rebuild path.
"""

from tony_tpu.serve.autoscaler import AutoscalePolicy, Autoscaler
from tony_tpu.serve.health import FleetSignals, HealthMonitor, Replica, ReplicaState
from tony_tpu.serve.router import FleetRouter

__all__ = [
    "AutoscalePolicy",
    "Autoscaler",
    "FleetRouter",
    "FleetSignals",
    "HealthMonitor",
    "Replica",
    "ReplicaState",
]
