"""Replicated serving control plane (docs/serving.md, "Replicated serving").

``tony serve --replicas N`` turns the single AM-supervised inference task
into a fault-tolerant fleet: N ``serve`` replicas under the ordinary gang
machinery, fronted by submitter-side pieces —

- :class:`~tony_tpu.serve.router.FleetRouter`: HTTP front door with
  session-affinity + least-outstanding balancing, health-checked
  failover/retry, and optional tail hedging;
- :class:`~tony_tpu.serve.sessions.SessionTable`: ``X-Tony-Session`` →
  replica pins (TTL + LRU, prompt-prefix hints) so the engine's paged
  prefix cache hits across multi-turn conversations and survives failover
  by re-pinning exactly once;
- :class:`~tony_tpu.serve.health.HealthMonitor`: AM-registry endpoint
  discovery (re-resolves across gang restarts) + active/passive per-replica
  health (healthy → draining → down);
- :class:`~tony_tpu.serve.autoscaler.Autoscaler`: queue-depth /
  slot-utilization driven replica retargeting through the AM's
  ``resize_jobtype`` elastic-rebuild path, draining the victim replica
  (DrainCourier contract) before a scale-down removes it;
- :class:`~tony_tpu.serve.loadgen.LoadGenerator`: open-loop multi-session
  load harness behind ``tony loadtest`` — sustained tokens/s, TTFT/token
  latency percentiles, reuse-loss accounting, and the gated
  ``SERVE_BENCH_*`` record family;
- :mod:`~tony_tpu.serve.disagg`: prefill/decode disaggregation (a second
  ``prefill`` jobtype hands finished KV pages to the decode tier over the
  paged-KV handoff contract) + the sharded router tier — N router workers
  behind one :class:`~tony_tpu.serve.disagg.RouterShardFront`, session pins
  sharded by consistent hash so they survive a router dying.
"""

from tony_tpu.serve.autoscaler import AutoscalePolicy, Autoscaler
from tony_tpu.serve.disagg import DisaggCoordinator, RouterShardFront, ShardRing
from tony_tpu.serve.health import FleetSignals, HealthMonitor, Replica, ReplicaState
from tony_tpu.serve.router import FleetRouter
from tony_tpu.serve.sessions import SessionPin, SessionTable

__all__ = [
    "AutoscalePolicy",
    "Autoscaler",
    "DisaggCoordinator",
    "FleetRouter",
    "FleetSignals",
    "HealthMonitor",
    "Replica",
    "ReplicaState",
    "RouterShardFront",
    "SessionPin",
    "SessionTable",
    "ShardRing",
]
