"""Open-loop multi-session load generator: the measurement half of the
serving data plane (``tony loadtest``).

The fleet can now pin sessions, drain replicas, and survive preemption —
none of which counts for anything until a harness measures sustained
tokens/s and tail TTFT under concurrent load and a gate holds the line.
This module is that harness:

- **open loop**: sessions arrive on a fixed-rate schedule (``rate``/s)
  regardless of how fast earlier ones complete — a slow fleet builds queue
  depth and its p99 shows it, instead of the closed-loop trap where a slow
  server throttles its own load generator into flattering numbers;
- **multi-session, multi-turn**: every session carries ``X-Tony-Session``
  and each turn's prompt extends the previous turn (prompt + generated
  tokens + fresh user tokens), exactly the shape the SessionTable + paged
  prefix cache are built for — pinned turns hit warm pages, and a mid-run
  failover shows up as re-pins (lost reuse), not errors;
- **prompt-length mix**: first-turn lengths draw from a weighted mix
  (``"16:0.5,64:0.3,256:0.2"``) so the fleet sees realistic prefill
  variance; an optional shared leading span exercises cross-session prefix
  reuse;
- **reported**: sustained tokens/s, TTFT and per-token-latency percentiles,
  error/re-pin/prefix-hit counts, and a ``SERVE_BENCH_*.json`` record
  (``tokens_per_sec`` ↑, ``ttft_p99_ms`` ↓) that ``tony bench --gate``
  enforces — the serving analogue of the MFU trajectory.

Everything is stdlib (threads + http.client): the harness must run anywhere
the router runs, including inside tier-1 CI against a CPU fleet.
"""

from __future__ import annotations

import bisect
import http.client
import json
import math
import os
import platform
import random
import threading
import time
import urllib.request
from dataclasses import dataclass, field
from typing import Any
from urllib.parse import urlsplit

#: the SERVE_BENCH family's headline metric name (gate trajectories compare
#: within one metric name only — this never collides with the train bench)
SERVE_BENCH_METRIC = "serve_tokens_per_sec"


def parse_prompt_mix(spec: str) -> list[tuple[int, float]]:
    """``"16:0.5,64:0.3,256:0.2"`` → [(16, .5), (64, .3), (256, .2)].
    Weights need not sum to 1 (they are normalized at draw time)."""
    out: list[tuple[int, float]] = []
    for part in (spec or "").split(","):
        part = part.strip()
        if not part:
            continue
        length, _, weight = part.partition(":")
        n = int(length)
        w = float(weight) if weight else 1.0
        if n <= 0 or w < 0:
            raise ValueError(f"bad prompt-mix entry {part!r} (want len:weight, len>0, weight>=0)")
        out.append((n, w))
    if not out or not any(w > 0 for _, w in out):
        raise ValueError(f"empty/zero-weight prompt mix {spec!r}")
    return out


def percentile(xs: list[float], p: float) -> float:
    """Nearest-rank percentile (0 for an empty list — absent metrics are
    dropped from the record before they reach the gate)."""
    if not xs:
        return 0.0
    ys = sorted(xs)
    i = min(int(len(ys) * p / 100.0), len(ys) - 1)
    return ys[i]


@dataclass
class LoadSpec:
    """One loadtest run's parameters (CLI flags / tony.serve.loadtest.*)."""

    url: str
    #: additional router endpoints (the sharded tier, ``tony serve
    #: --routers N`` driven WITHOUT the front): sessions spread across
    #: ``(url,) + urls`` deterministically by session index, and each
    #: session stays on its router so pins live in exactly one shard table
    urls: tuple = ()
    rate: float = 4.0          # session arrivals per second (open loop)
    sessions: int = 16
    turns: int = 3
    prompt_mix: list[tuple[int, float]] = field(
        default_factory=lambda: [(16, 0.5), (64, 0.3), (256, 0.2)])
    max_tokens: int = 16
    stream: bool = True
    shared_prefix: int = 0     # leading tokens shared by EVERY session
    turn_tokens: int = 8       # fresh "user" tokens appended per follow-up turn
    vocab: int = 1000          # token id range for synthetic prompts
    timeout_s: float = 120.0   # per-request client deadline
    seed: int = 0
    profile: str = "uniform"   # arrival shape: "uniform" | "diurnal"
    diurnal_amp: float = 3.0   # diurnal peak rate = (1 + amp) x the trough

    def all_urls(self) -> tuple:
        """Every endpoint this run drives (primary first, deduplicated)."""
        seen = []
        for u in (self.url, *self.urls):
            u = (u or "").rstrip("/")
            if u and u not in seen:
                seen.append(u)
        return tuple(seen)

    def session_url(self, idx: int) -> str:
        """The endpoint session ``idx`` sticks to for its whole lifetime."""
        urls = self.all_urls()
        return urls[idx % len(urls)]


def arrival_offsets(sessions: int, rate: float, profile: str = "uniform",
                    amp: float = 3.0) -> list[float]:
    """Session start offsets (seconds from t0) for one run.

    ``uniform`` is the classic open loop: fixed ``1/rate`` spacing.
    ``diurnal`` keeps the SAME total duration (``sessions/rate``) but draws
    arrivals from a squared-sine rate shape — quiet shoulders, a mid-run
    spike peaking at ``(1+amp)x`` the trough — by inverting the shape's
    cumulative mass on a fixed grid. Deterministic (no RNG): the spike's
    timing is part of the spec, so an SLO burn e2e can point at it.
    """
    if sessions <= 0:
        return []
    if rate <= 0:
        return [0.0] * sessions
    total = sessions / rate
    if profile != "diurnal":
        return [i / rate for i in range(sessions)]
    grid = 512
    cum: list[float] = []
    s = 0.0
    for j in range(grid):
        s += 1.0 + amp * math.sin(math.pi * (j + 0.5) / grid) ** 2
        cum.append(s)
    return [
        bisect.bisect_left(cum, (i + 0.5) / sessions * s) / grid * total
        for i in range(sessions)
    ]


@dataclass
class Turn:
    """One request's measured outcome."""

    session: int
    turn: int
    ok: bool
    status: int = 0
    error: str = ""
    replica: str = ""
    request_id: str = ""       # router-assigned X-Tony-Request-Id echo
    tokens: int = 0
    ttft_ms: float = 0.0       # first generated-token fanout (stream) / full reply
    latency_ms: float = 0.0
    pinned: bool = False       # same replica as the session's previous turn


@dataclass
class LoadReport:
    """Aggregated run outcome + the SERVE_BENCH record emitter."""

    spec: LoadSpec
    turns: list[Turn]
    wall_s: float
    router_before: dict[str, Any] | None = None
    router_after: dict[str, Any] | None = None

    # ------------------------------------------------------------ derived
    @property
    def ok_turns(self) -> list[Turn]:
        return [t for t in self.turns if t.ok]

    @property
    def errors(self) -> list[Turn]:
        return [t for t in self.turns if not t.ok]

    @property
    def tokens_total(self) -> int:
        return sum(t.tokens for t in self.ok_turns)

    @property
    def tokens_per_sec(self) -> float:
        return self.tokens_total / self.wall_s if self.wall_s > 0 else 0.0

    def _router_delta(self, *path: str) -> float | None:
        """after - before for one /stats field; None when unmeasurable —
        absent on either side, or NEGATIVE (the fleet aggregate only sums
        HEALTHY replicas and per-replica counters reset on restart, so a
        run spanning a drain/failover can shrink the aggregate; a garbage
        delta must not reach a checked-in SERVE_BENCH record)."""
        a, b = self.router_before, self.router_after
        for key in path:
            a = a.get(key) if isinstance(a, dict) else None
            b = b.get(key) if isinstance(b, dict) else None
        if isinstance(a, (int, float)) and isinstance(b, (int, float)):
            delta = float(b) - float(a)
            return delta if delta >= 0 else None
        return None

    def to_dict(self) -> dict[str, Any]:
        ttfts = [t.ttft_ms for t in self.ok_turns if t.ttft_ms > 0]
        lats = [t.latency_ms for t in self.ok_turns]
        tok_lat = [
            (t.latency_ms - t.ttft_ms) / (t.tokens - 1)
            for t in self.ok_turns if t.tokens > 1 and t.ttft_ms > 0
        ]
        followups = [t for t in self.ok_turns if t.turn > 0]
        out: dict[str, Any] = {
            "sessions": self.spec.sessions,
            "turns_per_session": self.spec.turns,
            "stream": self.spec.stream,
            "rate_per_s": self.spec.rate,
            "profile": self.spec.profile,
            "wall_s": round(self.wall_s, 3),
            "requests_ok": len(self.ok_turns),
            "requests_failed": len(self.errors),
            "tokens_total": self.tokens_total,
            "tokens_per_sec": round(self.tokens_per_sec, 2),
            "ttft_p50_ms": round(percentile(ttfts, 50), 2),
            "ttft_p95_ms": round(percentile(ttfts, 95), 2),
            "ttft_p99_ms": round(percentile(ttfts, 99), 2),
            "latency_p50_ms": round(percentile(lats, 50), 2),
            "latency_p99_ms": round(percentile(lats, 99), 2),
            "token_latency_p50_ms": round(percentile(tok_lat, 50), 3),
            "pinned_followup_turns": sum(1 for t in followups if t.pinned),
            "followup_turns": len(followups),
        }
        repins = self._router_delta("router", "session_repins")
        if repins is not None:
            out["session_repins"] = int(repins)  # reuse LOST to failover
        hits = self._router_delta("fleet", "prefix_hit_tokens")
        if hits is not None:
            out["prefix_hit_tokens"] = int(hits)
        # disaggregated fleets only: pages adopted through the prefill→
        # decode handoff during the run, and the coordinator's observed
        # handoff latency (the "handoff" phase of the serve.request chain)
        adopted = self._router_delta("fleet", "kv_handoff_adopted")
        if adopted is not None and adopted > 0:
            out["kv_handoff_pages"] = int(adopted)
        dis = (self.router_after or {}).get("disagg")
        if isinstance(dis, dict):
            for k in ("handoff_p50_ms", "handoff_p95_ms"):
                if isinstance(dis.get(k), (int, float)):
                    out[k] = dis[k]
        # worst-offender exemplars: the slowest TTFTs with the router's
        # request ids, so a bad tail is greppable straight into the span
        # chain / TTFT histogram exemplars (docs/observability.md)
        worst = sorted(
            (t for t in self.ok_turns if t.ttft_ms > 0),
            key=lambda t: -t.ttft_ms)[:5]
        if worst:
            out["worst_ttft"] = [
                {"ttft_ms": round(t.ttft_ms, 2), "request_id": t.request_id,
                 "session": t.session, "turn": t.turn, "replica": t.replica}
                for t in worst
            ]
        if self.errors:
            out["first_errors"] = [
                {"session": t.session, "turn": t.turn,
                 "status": t.status, "error": t.error[:200]}
                for t in self.errors[:5]
            ]
        return out

    def to_bench_record(self, round_n: int, baseline_tokens_per_sec: float | None = None,
                        rc: int = 0, slo_verdict: str | None = None,
                        budget_burned_pct: float | None = None) -> dict[str, Any]:
        """The ``SERVE_BENCH_r<N>.json`` wrapper ``tony bench --gate``
        enforces: headline = sustained tokens/s (↑), with ``ttft_p99_ms``
        gated downward alongside it. When the run was measured against an
        SLO (``tony slo verdict``), ``slo_verdict`` becomes a must-be-PASS
        contract and ``budget_burned_pct`` gates downward."""
        d = self.to_dict()
        vs = (self.tokens_per_sec / baseline_tokens_per_sec
              if baseline_tokens_per_sec else 1.0)
        parsed = {
            "metric": SERVE_BENCH_METRIC,
            "value": round(self.tokens_per_sec, 2),
            "unit": "tok/s",
            "vs_baseline": round(vs, 4),
            **{k: d[k] for k in (
                "tokens_per_sec", "ttft_p50_ms", "ttft_p95_ms", "ttft_p99_ms",
                "token_latency_p50_ms", "requests_ok", "requests_failed",
                "sessions", "turns_per_session", "stream", "rate_per_s",
                "wall_s",
            )},
        }
        for opt in ("session_repins", "prefix_hit_tokens", "profile",
                    "kv_handoff_pages", "handoff_p50_ms", "handoff_p95_ms"):
            if opt in d:
                parsed[opt] = d[opt]
        if slo_verdict is not None:
            parsed["slo_verdict"] = str(slo_verdict)
        if budget_burned_pct is not None:
            parsed["budget_burned_pct"] = round(float(budget_burned_pct), 3)
        # hardware provenance (same discipline as cbench records): the gate
        # only trend-compares rounds measured on the same fingerprint
        parsed["machine"] = {"cpus": os.cpu_count() or 0,
                             "arch": platform.machine()}
        return {"n": int(round_n), "rc": int(rc), "parsed": parsed}


class LoadGenerator:
    """Threaded open-loop driver over one :class:`LoadSpec`."""

    def __init__(self, spec: LoadSpec):
        self.spec = spec
        self._results: list[Turn] = []
        self._lock = threading.Lock()

    def completed(self) -> int:
        """Turns finished so far (ok or failed) — a live progress signal for
        callers deriving deadlines from observed progress instead of a fixed
        stopwatch (the e2e suites extend their waits while this advances)."""
        with self._lock:
            return len(self._results)

    # ------------------------------------------------------------ plumbing
    def _router_stats(self) -> dict[str, Any] | None:
        try:
            with urllib.request.urlopen(self.spec.url + "/stats", timeout=10) as resp:
                return json.loads(resp.read())
        except Exception:  # noqa: BLE001 — a bare replica has /stats too, but
            return None    # reuse-loss accounting is best-effort either way

    def _post(self, body: dict[str, Any], session_id: str,
              url: str | None = None) -> tuple[int, dict, Any]:
        """One POST /v1/completions. Returns (status, headers, parsed-or-
        stream-handle); streaming responses return the live HTTPResponse."""
        parts = urlsplit(url or self.spec.url)
        conn = http.client.HTTPConnection(
            parts.hostname, parts.port, timeout=self.spec.timeout_s)
        payload = json.dumps(body).encode()
        conn.request("POST", "/v1/completions", payload, {
            "Content-Type": "application/json",
            "X-Tony-Session": session_id,
        })
        resp = conn.getresponse()
        headers = {k: v for k, v in resp.getheaders()}
        if (headers.get("Content-Type") or "").startswith("text/event-stream"):
            return resp.status, headers, (conn, resp)
        data = resp.read()
        conn.close()
        try:
            return resp.status, headers, json.loads(data)
        except ValueError:
            return resp.status, headers, {"error": data[:200].decode("latin-1")}

    # ------------------------------------------------------------- session
    def _run_session(self, idx: int, start_at: float, t0: float,
                     rng: random.Random) -> None:
        # wait for this session's open-loop arrival slot
        delay = start_at - (time.monotonic() - t0)
        if delay > 0:
            time.sleep(delay)
        spec = self.spec
        session_id = f"lt-{spec.seed}-{idx}"
        url = spec.session_url(idx)
        lengths = [n for n, _ in spec.prompt_mix]
        weights = [w for _, w in spec.prompt_mix]
        first_len = rng.choices(lengths, weights=weights, k=1)[0]
        shared = list(range(1, spec.shared_prefix + 1))
        prompt = shared + [
            rng.randrange(1, spec.vocab)
            for _ in range(max(first_len - len(shared), 1))
        ]
        last_replica = ""
        for turn in range(spec.turns):
            result = Turn(session=idx, turn=turn, ok=False)
            req = {
                "prompt_tokens": prompt,
                "max_tokens": spec.max_tokens,
                "stream": spec.stream,
            }
            t_start = time.monotonic()
            try:
                status, headers, payload = self._post(req, session_id, url)
                result.status = status
                result.replica = headers.get("X-Tony-Replica", "")
                result.request_id = headers.get("X-Tony-Request-Id", "")
                if spec.stream and isinstance(payload, tuple):
                    conn, resp = payload
                    try:
                        toks = self._drain_sse(resp, result, t_start)
                    finally:
                        conn.close()
                elif status == 200 and isinstance(payload, dict):
                    toks = list(payload.get("tokens") or [])
                    result.ttft_ms = (time.monotonic() - t_start) * 1000
                else:
                    toks = None
                    result.error = str((payload or {}).get("error", f"HTTP {status}"))
                if toks is not None:
                    result.latency_ms = (time.monotonic() - t_start) * 1000
                    result.tokens = len(toks)
                    result.ok = True
                    result.pinned = bool(last_replica) and result.replica == last_replica
                    last_replica = result.replica or last_replica
                    # multi-turn growth: next prompt = this conversation so
                    # far + fresh user tokens — the prefix the pin keeps warm
                    prompt = prompt + toks + [
                        rng.randrange(1, spec.vocab) for _ in range(spec.turn_tokens)
                    ]
            except Exception as e:  # noqa: BLE001 — an error IS a data point
                result.error = repr(e)
                result.latency_ms = (time.monotonic() - t_start) * 1000
            with self._lock:
                self._results.append(result)

    def _drain_sse(self, resp, result: Turn, t_start: float) -> list[int] | None:
        """Consume one SSE stream; fills ttft on the first token event.
        Returns the final token list, or None on an in-stream error."""
        final: list[int] | None = None
        first = True
        buf = b""
        while True:
            chunk = resp.read1(8192)
            if not chunk:
                break
            buf += chunk
            while b"\n\n" in buf:
                event, buf = buf.split(b"\n\n", 1)
                line = event.strip()
                if not line.startswith(b"data: "):
                    continue
                obj = json.loads(line[6:])
                if obj.get("error"):
                    result.error = str(obj["error"])
                    return None
                if first and obj.get("tokens"):
                    result.ttft_ms = (time.monotonic() - t_start) * 1000
                    first = False
                if obj.get("finished"):
                    final = list(obj.get("tokens") or [])
                    return final
        if final is None:
            result.error = "stream truncated (no finished event)"
        return final

    # ----------------------------------------------------------------- run
    def run(self) -> LoadReport:
        spec = self.spec
        before = self._router_stats()
        rngs = [random.Random((spec.seed << 20) ^ i) for i in range(spec.sessions)]
        offsets = arrival_offsets(
            spec.sessions, spec.rate, spec.profile, spec.diurnal_amp)
        t0 = time.monotonic()
        threads = [
            threading.Thread(
                target=self._run_session,
                args=(i, offsets[i], t0, rngs[i]),
                name=f"loadgen-{i}", daemon=True)
            for i in range(spec.sessions)
        ]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        wall = time.monotonic() - t0
        after = self._router_stats()
        with self._lock:
            results = sorted(self._results, key=lambda r: (r.session, r.turn))
        return LoadReport(spec=spec, turns=results, wall_s=wall,
                          router_before=before, router_after=after)
