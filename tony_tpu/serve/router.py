"""Fleet router: the HTTP front door of a replicated ``tony serve`` job.

Runs in the submitting process (the notebook-proxy shape, SURVEY.md §3.4:
the submitter terminates user traffic locally and reaches the containers
through AM-registered URLs), in front of N ``serve`` replicas:

- **balancing**: least-outstanding-requests over HEALTHY replicas (ties →
  lowest index). UNKNOWN replicas (no probe verdict yet) are picked only
  when nothing HEALTHY exists — optimistic first-touch after a restart.
- **session affinity** (:mod:`tony_tpu.serve.sessions`): requests carrying
  ``X-Tony-Session`` stick to the replica that served the session's first
  turn while it stays routable, so the engine's paged prefix cache actually
  hits across a multi-turn conversation; new sessions whose prompt shares a
  known leading page are steered to the replica already holding it. A
  pinned replica going un-routable (crash, DRAINING, scale-down) re-pins
  the session on its next turn — exactly once, counted by
  ``tony_router_session_repins_total`` because a re-pin is one lost warm
  prefill.
- **failover**: a replica-level failure (connect refused/reset, response
  5xx) marks the replica through the :class:`HealthMonitor` and retries the
  request on another replica — engine requests are stateless, so
  completions are idempotent and safe to replay as long as no response
  byte has reached the client. When the whole fleet is down (gang restart
  in flight) the router WAITS for a replica to return, bounded by
  ``tony.serve.failover-deadline-ms`` — a replica crash costs the client
  latency, never an error.
- **hedging** (optional, non-streaming only): once an in-flight request
  outlives the p-th percentile of recent latencies
  (``tony.serve.hedge-percentile``, floored at ``hedge-min-ms``), the same
  request is fired at a second replica and the first response wins — the
  tail of a slow/overloaded replica stops defining the fleet's tail.

Client-level outcomes (400 bad request, 404, 429 overloaded, 504 deadline)
are forwarded verbatim, never retried. Responses carry ``X-Tony-Replica``
with the serving replica's index.

Observability: every request runs under a ``router.request`` span with one
``router.attempt`` child per replica try (job trace, joined via the
submit-span parent); request/retry/hedge counters and per-replica latency
histograms record into the process ``obs`` registry, which the submitter
pushes to the AM (``push_client_metrics``) for the portal's ``/metrics``.
Tracing disabled (the default) stays allocation-free on the hot path.
"""

from __future__ import annotations

import http.client
import itertools
import json
import queue
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Any
from urllib.parse import urlsplit

from tony_tpu.obs import metrics as obs_metrics
from tony_tpu.obs import trace as obs_trace
from tony_tpu.serve.health import HealthMonitor, Replica, ReplicaState
from tony_tpu.serve import sessions as sessions_mod
from tony_tpu.serve.sessions import SessionTable

_REQUESTS = obs_metrics.counter(
    "tony_router_requests_total", "routed requests by outcome", labelnames=("outcome",))
_RETRIES = obs_metrics.counter(
    "tony_router_retries_total", "replica failovers (request replayed on another replica)")
_HEDGES = obs_metrics.counter(
    "tony_router_hedges_total", "hedge requests fired at a second replica")
_HEDGE_WINS = obs_metrics.counter(
    "tony_router_hedge_wins_total", "hedged requests won by the second replica")
_REPLICA_LATENCY = obs_metrics.histogram(
    "tony_router_replica_latency_seconds",
    "per-replica request latency through the router", labelnames=("replica",))

#: headers copied from the winning replica response to the client
_FORWARD_HEADERS = ("Content-Type", "Retry-After", "Cache-Control")


class _AttemptFailed(Exception):
    """Replica-level failure (retryable on another replica)."""

    def __init__(self, replica: Replica, reason: str, hard: bool):
        super().__init__(reason)
        self.replica = replica
        self.hard = hard  # connection-level (process gone) vs 5xx


class _Latencies:
    """Rolling window of recent non-streaming latencies → hedge threshold."""

    def __init__(self, size: int = 512, min_samples: int = 20):
        self._lock = threading.Lock()
        self._window: list[float] = []
        self._size = size
        self._min_samples = min_samples

    def observe(self, seconds: float) -> None:
        with self._lock:
            self._window.append(seconds)
            if len(self._window) > self._size:
                del self._window[: len(self._window) - self._size]

    def percentile(self, p: float) -> float | None:
        with self._lock:
            if len(self._window) < self._min_samples:
                return None
            xs = sorted(self._window)
        i = min(int(len(xs) * p / 100.0), len(xs) - 1)
        return xs[i]


class FleetRouter:
    """HTTP front door over a :class:`HealthMonitor`'s fleet view."""

    def __init__(
        self,
        health: HealthMonitor,
        port: int = 0,
        host: str = "127.0.0.1",
        retries: int = 3,
        failover_deadline_s: float = 120.0,
        hedge_percentile: float = 0.0,
        hedge_min_s: float = 0.05,
        connect_timeout_s: float = 5.0,
        replica_timeout_s: float = 300.0,
        sessions: SessionTable | None = None,
        slo_ttft_threshold_ms: float | None = None,
        disagg: Any | None = None,
    ):
        self.health = health
        #: DisaggCoordinator (serve/disagg.py) — when set, completions
        #: requests carrying prompt_tokens fire a prefill leg at the prefill
        #: tier before the decode attempt; strictly best-effort
        self.disagg = disagg
        #: session-affinity table (None → a default-config table; pass an
        #: explicitly-configured one from tony.serve.session.* keys)
        self.sessions = sessions if sessions is not None else SessionTable()
        self.retries = max(int(retries), 0)
        self.failover_deadline_s = failover_deadline_s
        self.hedge_percentile = hedge_percentile
        self.hedge_min_s = hedge_min_s
        # connect is bounded TIGHT (a silently-dead host must fail over in
        # seconds, not hold the client for the full read budget); the read
        # timeout stays long — buffered long completions are legitimate
        self.connect_timeout_s = connect_timeout_s
        self.replica_timeout_s = replica_timeout_s
        self.started_s = time.time()
        self._latencies = _Latencies()
        # request ids: every request through the front door gets one (or
        # keeps the client's X-Tony-Request-Id) — the key that joins the
        # router span, the replica's queue→prefill→decode span chain, and
        # the TTFT worst-offender exemplars. itertools.count is atomic in
        # CPython, so handler threads need no lock here.
        self._rid_prefix = f"{int(self.started_s * 1000) & 0xFFFFFFFF:08x}"
        self._rid_seq = itertools.count(1)
        if slo_ttft_threshold_ms and slo_ttft_threshold_ms > 0:
            # SLO-aligned bucket edge: good/bad latency counts become exact
            _REPLICA_LATENCY.ensure_bucket(float(slo_ttft_threshold_ms) / 1000.0)
        router = self

        class Handler(BaseHTTPRequestHandler):
            def log_message(self, *a) -> None:  # quiet
                pass

            def do_GET(self) -> None:  # noqa: N802
                router._handle_get(self)

            def do_POST(self) -> None:  # noqa: N802
                router._handle_post(self)

        self._httpd = ThreadingHTTPServer((host, port), Handler)
        self._thread = threading.Thread(
            target=self._httpd.serve_forever, name="fleet-router", daemon=True)

    # ------------------------------------------------------------ lifecycle
    @property
    def url(self) -> str:
        host, port = self._httpd.server_address[:2]
        return f"http://{host}:{port}"

    def start(self) -> "FleetRouter":
        self._thread.start()
        return self

    def stop(self) -> None:
        self._httpd.shutdown()
        self._httpd.server_close()

    # ----------------------------------------------------------- GET pages
    def _handle_get(self, h: BaseHTTPRequestHandler) -> None:
        if h.path == "/healthz":
            sig = self.health.fleet_signals()
            _reply_json(h, 200 if sig.replicas_healthy else 503, {
                "ok": sig.replicas_healthy > 0,
                "replicas_healthy": sig.replicas_healthy,
                "replicas_known": sig.replicas_known,
            })
        elif h.path == "/stats":
            _reply_json(h, 200, self.stats())
        elif h.path == "/fleet":
            _reply_json(h, 200, self.health.fleet_info())
        elif h.path == "/sessions":
            _reply_json(h, 200, self.sessions.to_info())
        else:
            _reply_json(h, 404, {"error": "not found"})

    def stats(self) -> dict[str, Any]:
        """Aggregated fleet counters + router-level totals."""
        self.sessions.sweep()  # opportunistic TTL pass (lookup expires lazily too)
        agg: dict[str, float] = {}
        per_replica = []
        for r in self.health.snapshot():
            per_replica.append(r.to_info())
            if r.state == ReplicaState.HEALTHY:
                for k in ("slots_total", "slots_active", "queue_depth",
                          "requests_done", "tokens_out", "tokens_delivered",
                          "prefix_hit_tokens", "pages_live", "pages_total",
                          "kv_handoff_exported", "kv_handoff_adopted"):
                    v = r.stats.get(k)
                    if isinstance(v, (int, float)):
                        agg[k] = agg.get(k, 0) + v
        out: dict[str, Any] = {
            "router": {
                "uptime_s": round(time.time() - self.started_s, 1),
                "requests_ok": _REQUESTS.value(outcome="ok"),
                "requests_forwarded": _REQUESTS.value(outcome="forwarded"),
                "requests_unavailable": _REQUESTS.value(outcome="unavailable"),
                "retries": _RETRIES.value(),
                "hedges": _HEDGES.value(),
                "hedge_wins": _HEDGE_WINS.value(),
                "sessions": len(self.sessions),
                "session_repins": sessions_mod.repins_total(),
            },
            "fleet": agg,
            "replicas": per_replica,
        }
        if self.disagg is not None:
            out["disagg"] = self.disagg.stats()
        return out

    # --------------------------------------------------------- POST → proxy
    def _handle_post(self, h: BaseHTTPRequestHandler) -> None:
        length = int(h.headers.get("Content-Length") or 0)
        body = h.rfile.read(length) if length else b""
        stream = False
        prompt_tokens = None
        try:
            req = json.loads(body or b"{}")
            stream = bool(req.get("stream", False))
            pt = req.get("prompt_tokens")
            if isinstance(pt, list):
                prompt_tokens = pt
        except (ValueError, AttributeError):
            pass  # the replica will answer 400; route it through anyway
        session_id = (h.headers.get("X-Tony-Session") or "").strip() or None
        rid = ((h.headers.get("X-Tony-Request-Id") or "").strip()
               or f"{self._rid_prefix}-{next(self._rid_seq):x}")
        with obs_trace.maybe_span("router.request", path=h.path, stream=stream,
                                  session=session_id, rid=rid):
            self._route(h, h.path, body, stream, session_id, prompt_tokens, rid)

    def _route(self, h: BaseHTTPRequestHandler, path: str, body: bytes, stream: bool,
               session_id: str | None = None,
               prompt_tokens: list[int] | None = None, rid: str = "") -> None:
        deadline = time.monotonic() + self.failover_deadline_s
        tried: set[int] = set()
        soft_failovers = 0
        prefill_done = False
        while True:
            replica = self._pick(tried, session_id, prompt_tokens)
            if replica is None:
                if tried:
                    tried.clear()  # every routable replica tried: start over
                    continue
                if time.monotonic() >= deadline:
                    _REQUESTS.inc(outcome="unavailable")
                    _reply_json(h, 503, {"error": "no healthy replica "
                                         f"(waited {self.failover_deadline_s:.0f}s)"},
                                rid=rid)
                    return
                # whole fleet down (gang restart in flight): wait for the
                # health monitor to resolve the relaunched endpoints
                time.sleep(0.1)
                continue
            if (self.disagg is not None and prompt_tokens and not prefill_done
                    and path.endswith("/completions")):
                # ONE prefill leg per request, not per failover attempt: the
                # leg warms the chosen decode replica's page pool; a decode
                # failover after the handoff simply recomputes (the pages
                # died with the replica), it must not re-run the leg
                prefill_done = True
                with obs_trace.maybe_span("router.prefill_leg", rid=rid,
                                          decode_replica=replica.index):
                    self.disagg.prefill(prompt_tokens, replica.url, rid)
            try:
                if stream:
                    self._attempt_stream(h, replica, path, body, rid)
                else:
                    status, headers, payload = self._attempt_hedged(
                        replica, tried, path, body, rid)
                    _relay(h, status, headers, payload)
                    _REQUESTS.inc(outcome="ok" if status == 200 else "forwarded")
                return
            except _AttemptFailed as e:
                # (the failure was already reported to the HealthMonitor at
                # the raise site — hedge legs report even when discarded)
                obs_trace.add_event(
                    "router.failover", replica=e.replica.index, reason=str(e)[:200])
                tried.add(e.replica.index)
                _RETRIES.inc()
                # only SOFT failovers (replica up but erroring) consume the
                # retry budget; hard (connection) failures wait out the
                # restart, bounded by the deadline above — a crash-window
                # hard failover must never pre-spend the 5xx budget
                if not e.hard:
                    soft_failovers += 1
                    if soft_failovers > self.retries:
                        # replaying a systematic failure forever would only
                        # amplify it
                        _REQUESTS.inc(outcome="failed")
                        _reply_json(h, 502, {"error": f"replicas failing: {e}"},
                                    rid=rid)
                        return

    # ------------------------------------------------------------ selection
    def _pick(self, exclude: set[int], session_id: str | None = None,
              prompt_tokens: list[int] | None = None) -> Replica | None:
        """Session-pinned replica first (while routable and untried), then
        least-outstanding HEALTHY; UNKNOWN (no probe verdict yet — e.g. just
        relaunched) only when nothing is HEALTHY. A sessionful pick updates
        the SessionTable: first turn pins, a failover pick re-pins (counted
        — each re-pin is one lost warm prefill)."""
        snap = self.health.snapshot()
        by_index = {r.index: r for r in snap}
        pin = self.sessions.lookup(session_id) if session_id else None
        if pin is not None:
            r = by_index.get(pin.replica_index)
            if r is not None and r.state.routable and r.index not in exclude:
                self.sessions.record_route("pinned")
                return r
        chosen = None
        outcome = "new"
        if pin is None and session_id:
            # brand-new session: steer a shared leading page (system prompt)
            # to the replica already holding it — hint only, never forced
            hinted = self.sessions.hint(prompt_tokens)
            if hinted is not None and hinted not in exclude:
                r = by_index.get(hinted)
                if r is not None and r.state == ReplicaState.HEALTHY:
                    chosen, outcome = r, "hinted"
        if chosen is None:
            for state in (ReplicaState.HEALTHY, ReplicaState.UNKNOWN):
                cands = [r for r in snap if r.state == state and r.index not in exclude]
                if cands:
                    chosen = min(cands, key=lambda r: (r.outstanding, r.index))
                    break
        if chosen is None:
            return None
        if session_id:
            if pin is not None and chosen.index != pin.replica_index:
                outcome = "repinned"
                obs_trace.add_event("router.session_repin", session=session_id,
                                    old=pin.replica_index, new=chosen.index)
            elif pin is not None:
                # same replica re-chosen through the fallback (e.g. the whole
                # fleet is UNKNOWN mid-restart): the pin held, not a re-pin
                outcome = "pinned"
            self.sessions.pin(session_id, chosen.index, prompt_tokens)
            self.sessions.record_route(outcome)
        return chosen

    # ------------------------------------------------------------- attempts
    def _fail(self, replica: Replica, reason: str, hard: bool) -> _AttemptFailed:
        """Build an _AttemptFailed AND report it to the HealthMonitor at the
        raise site — so hedge legs whose exception is discarded (the other
        leg won) still mark their replica."""
        self.health.report_failure(replica, hard=hard)
        if hard:
            # the process is gone: its warm prefixes went with it — stop
            # steering NEW sessions there (existing pins re-pin lazily)
            self.sessions.drop_replica(replica.index)
        return _AttemptFailed(replica, reason, hard)

    def _open(self, replica: Replica, path: str, body: bytes, rid: str = ""):
        """One POST to a replica → live (conn, response). Connection-level
        failures raise _AttemptFailed(hard=True)."""
        parts = urlsplit(replica.url)
        headers = {"Content-Type": "application/json"}
        if rid:
            # the id the replica's span chain + TTFT exemplars key on
            headers["X-Tony-Request-Id"] = rid
        try:
            conn = http.client.HTTPConnection(
                parts.hostname, parts.port, timeout=self.connect_timeout_s)
            conn.connect()
            conn.sock.settimeout(self.replica_timeout_s)
            conn.request("POST", path, body, headers)
            resp = conn.getresponse()
        except (ConnectionError, OSError) as e:
            raise self._fail(replica, f"connect/send failed: {e}", hard=True) from e
        # 504 is the REPLICA's verdict on the client's own deadline
        # (serving_http maps "deadline exceeded" to 504): a client-level
        # outcome to forward verbatim, not a replica failure — retrying would
        # restart the deadline clock on another replica and answer 502
        if resp.status >= 500 and resp.status != 504:
            payload = resp.read()
            conn.close()
            if resp.status == 503 and b"draining" in payload:
                # lifecycle, not failure: the replica is refusing admissions
                # while it drains (preemption notice / scale-down victim /
                # SIGTERM window). Shed it and retry elsewhere WITHOUT
                # consuming the soft-failover budget — a drain must never
                # become a client-visible 502, and marking it DOWN would
                # misread an orderly handoff as an outage.
                self.health.report_draining(replica)
                raise _AttemptFailed(replica, "replica draining", hard=True)
            raise self._fail(
                replica, f"replica answered {resp.status}: {payload[:200]!r}", hard=False)
        return conn, resp

    def _attempt_once(self, replica: Replica, path: str, body: bytes,
                      rid: str = "") -> tuple[int, dict, bytes]:
        """Buffered (non-streaming) attempt; returns (status, headers, body)."""
        with self.health.lock:
            replica.outstanding += 1
        t0 = time.perf_counter()
        try:
            with obs_trace.maybe_span("router.attempt", replica=replica.index,
                                      rid=rid):
                conn, resp = self._open(replica, path, body, rid)
                try:
                    payload = resp.read()
                except (ConnectionError, OSError) as e:
                    raise self._fail(replica, f"read failed: {e}", hard=True) from e
                finally:
                    conn.close()
        finally:
            with self.health.lock:
                replica.outstanding -= 1
        took = time.perf_counter() - t0
        _REPLICA_LATENCY.observe(took, exemplar=rid or None,
                                 replica=str(replica.index))
        if resp.status == 200:
            self._latencies.observe(took)
        self.health.report_success(replica)
        headers = {k: resp.headers[k] for k in _FORWARD_HEADERS if resp.headers.get(k)}
        headers["X-Tony-Replica"] = str(replica.index)
        if rid:
            headers["X-Tony-Request-Id"] = rid
        return resp.status, headers, payload

    def _attempt_hedged(
        self, replica: Replica, tried: set[int], path: str, body: bytes,
        rid: str = "",
    ) -> tuple[int, dict, bytes]:
        """Non-streaming attempt with optional tail hedging. The primary
        failure mode propagates as _AttemptFailed only when no hedge is in
        flight or the hedge failed too."""
        threshold = None
        if self.hedge_percentile > 0:
            p = self._latencies.percentile(self.hedge_percentile)
            if p is not None:
                threshold = max(p, self.hedge_min_s)
        if threshold is None:
            return self._attempt_once(replica, path, body, rid)

        results: "queue.Queue[tuple[bool, Any, Replica]]" = queue.Queue()

        def run(r: Replica) -> None:
            try:
                results.put((True, self._attempt_once(r, path, body, rid), r))
            except _AttemptFailed as e:
                results.put((False, e, r))

        threading.Thread(target=run, args=(replica,), daemon=True).start()
        in_flight = 1
        hedge_fired = False
        try:
            ok, payload, who = results.get(timeout=threshold)
        except queue.Empty:
            backup = self._pick_hedge(exclude=tried | {replica.index})
            if backup is not None:
                _HEDGES.inc()
                hedge_fired = True
                obs_trace.add_event("router.hedge", primary=replica.index,
                                    backup=backup.index)
                threading.Thread(target=run, args=(backup,), daemon=True).start()
                in_flight += 1
            ok, payload, who = results.get()
        in_flight -= 1
        if not ok and in_flight:
            # first finisher failed (already health-reported at the raise
            # site); exclude it from this request and give the other leg
            # its chance
            tried.add(payload.replica.index)
            ok, payload, who = results.get()
            in_flight -= 1
        if not ok:
            raise payload  # _AttemptFailed from the losing leg
        if hedge_fired and who is not replica:
            _HEDGE_WINS.inc()
        return payload

    def _pick_hedge(self, exclude: set[int]) -> Replica | None:
        healthy = [r for r in self.health.snapshot()
                   if r.state == ReplicaState.HEALTHY and r.index not in exclude]
        return min(healthy, key=lambda r: (r.outstanding, r.index)) if healthy else None

    # ------------------------------------------------------------ streaming
    def _attempt_stream(
        self, h: BaseHTTPRequestHandler, replica: Replica, path: str, body: bytes,
        rid: str = "",
    ) -> None:
        """SSE relay. Retryable only until the response status is known; once
        bytes flow to the client a replica death truncates the stream (the
        client sees the connection close, exactly as if it held the replica
        connection itself)."""
        with self.health.lock:
            replica.outstanding += 1
        t0 = time.perf_counter()
        try:
            with obs_trace.maybe_span("router.attempt", replica=replica.index,
                                      stream=True, rid=rid):
                conn, resp = self._open(replica, path, body, rid)
                try:
                    if not (resp.headers.get("Content-Type") or "").startswith(
                        "text/event-stream"
                    ):
                        # non-streaming reply to a stream request (400, 429,
                        # 503-draining...): buffered forward, still retryable
                        try:
                            payload = resp.read()
                        except (ConnectionError, OSError) as e:
                            raise self._fail(
                                replica, f"read failed: {e}", hard=True) from e
                        headers = {k: resp.headers[k] for k in _FORWARD_HEADERS
                                   if resp.headers.get(k)}
                        headers["X-Tony-Replica"] = str(replica.index)
                        if rid:
                            headers["X-Tony-Request-Id"] = rid
                        _relay(h, resp.status, headers, payload)
                        _REQUESTS.inc(outcome="ok" if resp.status == 200 else "forwarded")
                        self.health.report_success(replica)
                        return
                    h.send_response(200)
                    h.send_header("Content-Type", resp.headers["Content-Type"])
                    h.send_header("Cache-Control", "no-cache")
                    h.send_header("X-Tony-Replica", str(replica.index))
                    if rid:
                        h.send_header("X-Tony-Request-Id", rid)
                    h.end_headers()
                    while True:
                        try:
                            chunk = resp.read1(8192)
                        except (ConnectionError, OSError):
                            # replica died mid-stream: the client sees the
                            # truncated stream; mark the replica so the next
                            # request doesn't need the active probe to notice
                            self.health.report_failure(replica, hard=True)
                            _REQUESTS.inc(outcome="truncated")
                            return
                        if not chunk:
                            break
                        try:
                            h.wfile.write(chunk)
                            h.wfile.flush()
                        except OSError:
                            conn.close()  # client went away: cancel upstream
                            _REQUESTS.inc(outcome="client_disconnect")
                            return
                    _REQUESTS.inc(outcome="ok")
                    self.health.report_success(replica)
                finally:
                    conn.close()
        finally:
            with self.health.lock:
                replica.outstanding -= 1
            _REPLICA_LATENCY.observe(
                time.perf_counter() - t0, exemplar=rid or None,
                replica=str(replica.index))


# ---------------------------------------------------------------- helpers
def _reply_json(h: BaseHTTPRequestHandler, status: int, obj: Any,
                rid: str = "") -> None:
    body = json.dumps(obj).encode()
    h.send_response(status)
    h.send_header("Content-Type", "application/json")
    h.send_header("Content-Length", str(len(body)))
    if rid:
        h.send_header("X-Tony-Request-Id", rid)
    h.end_headers()
    h.wfile.write(body)


def _relay(h: BaseHTTPRequestHandler, status: int, headers: dict, body: bytes) -> None:
    h.send_response(status)
    for k, v in headers.items():
        h.send_header(k, v)
    h.send_header("Content-Length", str(len(body)))
    h.end_headers()
    h.wfile.write(body)
