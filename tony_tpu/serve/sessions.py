"""Session-affinity table: pin a conversation to the replica that holds its KV.

The engine's paged prefix cache (models/paged_cache.py) makes turn N+1 of a
conversation nearly free — *on the replica that served turns 1..N*. The
fleet router's least-outstanding balancing is blind to that: it scatters a
session's turns across replicas and every turn pays a cold prefill. The
:class:`SessionTable` closes the gap:

- a client that sends ``X-Tony-Session: <id>`` is **pinned** to the replica
  that served its first turn; while that replica stays routable every later
  turn lands on the warm prefix cache;
- entries expire after ``tony.serve.session.ttl-ms`` of inactivity and the
  table is LRU-capped at ``tony.serve.session.max-sessions`` — a session
  table must never become the fleet's memory leak;
- **prompt-prefix-hash hints**: each pin remembers a hash of the prompt's
  leading page (the same page granularity the engine's prefix cache keys
  on). A NEW session whose first prompt shares that prefix (shared system
  prompt, few-shot header) is steered to a replica already holding it, so
  cross-session sharing survives the router too;
- **failover re-pin**: when a pinned replica stops being routable (crash,
  DRAINING under a preemption drain, scale-down) the next turn re-pins to a
  live replica — exactly once per failover, counted by
  ``tony_router_session_repins_total`` because every re-pin is lost KV reuse
  (the new replica pays one cold prefill) that capacity planning should see.

Thread safety: one lock around the table; the router calls from its HTTP
handler threads. All decisions are O(1) dict/OrderedDict operations — this
sits on the request hot path.
"""

from __future__ import annotations

import hashlib
import threading
import time
from collections import OrderedDict
from dataclasses import dataclass
from typing import Any

from tony_tpu.obs import metrics as obs_metrics

_REPINS = obs_metrics.counter(
    "tony_router_session_repins_total",
    "sessions re-pinned after their replica stopped being routable "
    "(each re-pin is one lost warm-prefix hit)")
_SESSIONS = obs_metrics.gauge(
    "tony_router_sessions", "live (unexpired) session pins in the router")
_AFFINITY = obs_metrics.counter(
    "tony_router_session_routes_total",
    "session-routed requests by how the replica was chosen",
    labelnames=("outcome",))  # pinned | repinned | new | hinted


def repins_total() -> float:
    """Lifetime re-pin count (the /stats payload's reuse-loss figure — the
    loadtest harness diffs it across a run)."""
    return _REPINS.value()


def record_repin() -> None:
    """Count a re-pin that happens OUTSIDE a SessionTable move — the router
    shard front re-assigning a session to a surviving shard after its shard
    died (serve/disagg.py). Same counter as the in-table move path: either
    way the session's next turn pays one cold routing decision, and capacity
    planning wants ONE number for that."""
    _REPINS.inc()


def prefix_fingerprint(prompt_tokens: list[int], span: int) -> str | None:
    """Content hash of the prompt's first ``span`` tokens (None when the
    prompt is shorter — too little shared material to steer on). Matches the
    engine's page-granular prefix keys in spirit: two prompts with the same
    fingerprint share at least one full cache page on a replica. Malformed
    tokens (non-ints, out of 64-bit range — the replica's 400 to answer,
    not ours to crash on) fingerprint as None."""
    if span <= 0 or len(prompt_tokens) < span:
        return None
    h = hashlib.sha256()
    try:
        h.update(b"".join(int(t).to_bytes(8, "little", signed=True)
                          for t in prompt_tokens[:span]))
    except (TypeError, ValueError, OverflowError):
        return None
    return h.hexdigest()


@dataclass
class SessionPin:
    """One session's affinity record."""

    session_id: str
    replica_index: int
    last_used_s: float
    prefix: str | None = None  # fingerprint of the session's first prompt page
    repins: int = 0

    def to_info(self) -> dict[str, Any]:
        return {
            "session": self.session_id,
            "replica": self.replica_index,
            "idle_s": round(time.time() - self.last_used_s, 1),
            "repins": self.repins,
        }


class SessionTable:
    """TTL + LRU map of session id → pinned replica, with prefix hints."""

    def __init__(self, ttl_s: float = 600.0, max_sessions: int = 10_000,
                 prefix_span: int = 256):
        self.ttl_s = max(float(ttl_s), 0.0)
        self.max_sessions = max(int(max_sessions), 1)
        self.prefix_span = int(prefix_span)
        self._lock = threading.Lock()
        #: insertion/recency order IS the LRU order (move_to_end on touch)
        self._pins: "OrderedDict[str, SessionPin]" = OrderedDict()
        #: prefix fingerprint → replica index of the most recent pin that
        #: carried it (hint only — never authoritative, never re-pinned)
        self._prefix_owner: dict[str, int] = {}
        #: fingerprint → count of LIVE pins carrying it; the hint survives
        #: until the last such pin is evicted (one session of N sharing a
        #: system prompt expiring must not blind new sessions to the other
        #: N-1 keeping the pages warm)
        self._prefix_live: dict[str, int] = {}
        #: gossiped hints from sibling router shards (serve/disagg.py):
        #: kept apart from _prefix_owner because they carry no local live-pin
        #: refcount — merging them into the owner map would corrupt the
        #: _prefix_live bookkeeping. LRU-capped at max_sessions.
        self._gossip: "OrderedDict[str, int]" = OrderedDict()

    # ------------------------------------------------------------- routing
    def lookup(self, session_id: str) -> SessionPin | None:
        """The live pin for a session (touches LRU recency), or None
        (unknown / expired)."""
        now = time.time()
        with self._lock:
            pin = self._pins.get(session_id)
            if pin is None:
                return None
            if self.ttl_s and now - pin.last_used_s > self.ttl_s:
                self._evict_locked(session_id)
                return None
            pin.last_used_s = now
            self._pins.move_to_end(session_id)
            return pin

    def pin(self, session_id: str, replica_index: int,
            prompt_tokens: list[int] | None = None) -> SessionPin:
        """Pin (or move) a session to ``replica_index``. A move of an
        existing pin is a failover re-pin: counted, because the new replica
        pays the cold prefill the pin existed to avoid."""
        now = time.time()
        with self._lock:
            pin = self._pins.get(session_id)
            if pin is not None and self.ttl_s and now - pin.last_used_s > self.ttl_s:
                self._evict_locked(session_id)
                pin = None
            if pin is None:
                pin = SessionPin(session_id, replica_index, now)
                if prompt_tokens:
                    pin.prefix = prefix_fingerprint(prompt_tokens, self.prefix_span)
                self._pins[session_id] = pin
                if pin.prefix is not None:
                    self._prefix_live[pin.prefix] = (
                        self._prefix_live.get(pin.prefix, 0) + 1)
                while len(self._pins) > self.max_sessions:
                    self._evict_locked(next(iter(self._pins)))
            elif pin.replica_index != replica_index:
                pin.replica_index = replica_index
                pin.repins += 1
                _REPINS.inc()
            pin.last_used_s = now
            self._pins.move_to_end(session_id)
            if pin.prefix is not None:
                self._prefix_owner[pin.prefix] = replica_index
            _SESSIONS.set(len(self._pins))
            return pin

    def hint(self, prompt_tokens: list[int] | None) -> int | None:
        """Replica index that most recently pinned a session with this
        prompt's leading-page fingerprint, or None. Used only for brand-new
        sessions: shared system prompts land where the prefix is warm."""
        if not prompt_tokens:
            return None
        fp = prefix_fingerprint(prompt_tokens, self.prefix_span)
        if fp is None:
            return None
        with self._lock:
            got = self._prefix_owner.get(fp)
            if got is None:
                got = self._gossip.get(fp)
            return got

    def record_route(self, outcome: str) -> None:
        """Exposition of how a session request was routed
        (pinned/repinned/new/hinted)."""
        _AFFINITY.inc(outcome=outcome)

    # -------------------------------------------------------------- gossip
    def export_hints(self) -> dict[str, int]:
        """Snapshot of the LOCALLY-OWNED prefix hints (fingerprint →
        replica) for replication to sibling router shards. Gossiped-in
        hints are excluded — re-exporting them would let a stale entry
        bounce between shards forever."""
        with self._lock:
            return dict(self._prefix_owner)

    def merge_hints(self, hints: dict[str, int]) -> int:
        """Adopt sibling shards' prefix hints. Local ownership wins (a
        local live pin is fresher than gossip); the gossip side table is
        LRU-capped at max_sessions. Returns how many entries were new."""
        added = 0
        with self._lock:
            for fp, idx in hints.items():
                if fp in self._prefix_owner:
                    continue
                if fp not in self._gossip:
                    added += 1
                self._gossip[fp] = int(idx)
                self._gossip.move_to_end(fp)
            while len(self._gossip) > self.max_sessions:
                self._gossip.popitem(last=False)
        return added

    # --------------------------------------------------------- maintenance
    def drop_replica(self, replica_index: int) -> int:
        """Forget prefix hints pointing at a replica that left the fleet
        (scale-down, gang restart). Pins stay — their next turn re-pins and
        is counted — but hints must not steer NEW sessions at a corpse.
        Returns the number of hints dropped."""
        with self._lock:
            stale = [fp for fp, idx in self._prefix_owner.items()
                     if idx == replica_index]
            for fp in stale:
                del self._prefix_owner[fp]
            gone = [fp for fp, idx in self._gossip.items()
                    if idx == replica_index]
            for fp in gone:
                del self._gossip[fp]
            return len(stale) + len(gone)

    def sweep(self) -> int:
        """Expire idle sessions (TTL); returns how many were evicted. The
        router calls this opportunistically — correctness never depends on
        it because lookup() expires lazily."""
        if not self.ttl_s:
            return 0
        now = time.time()
        with self._lock:
            dead = [sid for sid, pin in self._pins.items()
                    if now - pin.last_used_s > self.ttl_s]
            for sid in dead:
                self._evict_locked(sid)
            _SESSIONS.set(len(self._pins))
            return len(dead)

    def _evict_locked(self, session_id: str) -> None:
        pin = self._pins.pop(session_id, None)
        if pin is not None and pin.prefix is not None:
            # the hint outlives THIS pin while any other live session still
            # carries the fingerprint — their pins keep the pages warm
            left = self._prefix_live.get(pin.prefix, 1) - 1
            if left > 0:
                self._prefix_live[pin.prefix] = left
            else:
                self._prefix_live.pop(pin.prefix, None)
                self._prefix_owner.pop(pin.prefix, None)

    # ------------------------------------------------------------- queries
    def __len__(self) -> int:
        with self._lock:
            return len(self._pins)

    def to_info(self, limit: int = 50) -> dict[str, Any]:
        with self._lock:
            pins = list(self._pins.values())
        return {
            "sessions": len(pins),
            "ttl_s": self.ttl_s,
            "max_sessions": self.max_sessions,
            "recent": [p.to_info() for p in pins[-limit:]],
        }
