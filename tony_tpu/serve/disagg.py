"""Disaggregated prefill/decode serving + the sharded router tier.

Prefill is compute-bound (one big batched matmul over the prompt), decode is
memory-bound (KV reads dominate); co-scheduling both phases on one replica
tier sizes the fleet for whichever bound is worse at the moment and wastes
the other resource. This module splits them into two jobtypes of ONE
application (``prefill`` + ``serve``, constants.PREFILL_JOB_NAME) over the
existing gang/RPC machinery, connected by a paged-KV transfer contract:

1. the router fires a **prefill leg** at a prefill replica
   (:class:`DisaggCoordinator` → ``POST /v1/prefill``) carrying the decode
   replica's URL;
2. the prefill replica runs the prompt for exactly one token, exports its
   finished full-prompt pages (:func:`export_prefix_pages` — match_prefix
   pins them, ``gather_pages`` reads them out, release unpins) and ships
   them (:func:`ship_pages` → ``POST /v1/kv/adopt``);
3. the decode replica adopts them (:func:`adopt_pages` — alloc → scatter →
   register → release parks the pages in its reuse pool, content-addressed
   under the same incremental prefix keys the engine computes at admission);
4. the router then routes the request to that decode replica, whose
   admission-time ``match_prefix`` finds the adopted pages and skips the
   prefill — ``prefix_hit_tokens`` and ``tony_serve_kv_handoff_total``
   account for it.

Every step degrades gracefully: a failed leg/ship/adopt costs one decode-
side recompute, never a client-visible error.

The second half is the **router shard tier**: N :class:`FleetRouter`
workers, each owning a shard of the session-pin space by consistent hash of
session id (:class:`ShardRing`), behind one :class:`RouterShardFront`
(``tony serve --routers N``). A shard dying moves only its arc of the ring:
surviving sessions keep their pins, the orphaned ones re-resolve to a live
shard with exactly-once re-pin accounting through the same
``tony_router_session_repins_total`` counter the in-table move path uses.
Prefix hints replicate between shards on the stats/housekeeping tick
(gossip-on-stats) so a shared system prompt steers correctly no matter
which shard admits the session.
"""

from __future__ import annotations

import base64
import bisect
import hashlib
import http.client
import itertools
import json
import threading
import time
from collections import OrderedDict, deque
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Any
from urllib.parse import urlsplit

from tony_tpu.obs import metrics as obs_metrics
from tony_tpu.obs import trace as obs_trace
from tony_tpu.serve import sessions as sessions_mod
from tony_tpu.serve.health import HealthMonitor, Replica, ReplicaState

# same instrument serving_http.py registers — the registry hands back the
# existing counter, so both sides account into one series without either
# module importing the other (serving_http lazy-imports us per request)
_KV_HANDOFF = obs_metrics.counter(
    "tony_serve_kv_handoff_total",
    "KV pages moved through the disaggregated prefill→decode handoff "
    "(side=exported|adopted)", labelnames=("side",))
_PREFILL_LEGS = obs_metrics.counter(
    "tony_router_prefill_legs_total",
    "disagg prefill legs fired by the router, by outcome "
    "(ok | refused | error | no_replica)", labelnames=("outcome",))
_SHARD_FAILOVERS = obs_metrics.counter(
    "tony_router_shard_failovers_total",
    "requests re-routed by the shard front after a router shard died")


# =========================================================================
# KV handoff: engine-side contract (runs via EngineServer.run_on_engine)
# =========================================================================

def export_prefix_pages(srv, prompt: list[int]) -> dict | None:
    """ENGINE THREAD ONLY. Read the full-prompt pages this engine holds for
    ``prompt`` out of the device pools into a wire payload, or None when
    nothing is resident (the prompt spans <1 page, or the pages were evicted
    between decode-done and export — both legal, both mean the decode side
    recomputes). Pages are pinned (match_prefix) across the device read and
    released after: the reuse pool must not evict them mid-gather."""
    import jax
    import jax.numpy as jnp

    from tony_tpu.models.paged_cache import gather_pages, prefix_keys

    eng = srv.engine
    keys = prefix_keys(prompt, eng.page_len)
    if not keys:
        return None
    pages = eng.allocator.match_prefix(keys)  # pins every matched page
    if not pages:
        return None
    try:
        pk, pv = gather_pages(eng.cache.k, eng.cache.v,
                              jnp.asarray(pages, jnp.int32), n=len(pages))
        pk, pv = jax.device_get((pk, pv))
    finally:
        for p in pages:
            eng.allocator.release(p)
    srv.kv_handoff_exported += len(pages)
    _KV_HANDOFF.inc(len(pages), side="exported")
    return {
        "page_len": int(eng.page_len),
        "dtype": str(pk.dtype),
        "shape": list(pk.shape),                       # [L, n, Hkv, page_len, Dh]
        "keys": [[int(j), d.hex()] for j, d in keys[:len(pages)]],
        "k": base64.b64encode(pk.tobytes()).decode("ascii"),
        "v": base64.b64encode(pv.tobytes()).decode("ascii"),
    }


def adopt_pages(srv, payload: dict) -> tuple[int, int]:
    """ENGINE THREAD ONLY. Adopt shipped pages into this engine's paged
    pool: alloc physical pages, scatter the shipped values in, register them
    under their content keys, and release — parking them in the reuse pool
    exactly like a retired request's prompt pages, where the next matching
    prompt's admission-time match_prefix resurrects them instead of
    recomputing. Returns ``(adopted, already_resident)``. Raises ValueError
    on a geometry/dtype mismatch (serving_http maps it to 400)."""
    import numpy as np

    import jax.numpy as jnp

    from tony_tpu.models.paged_cache import scatter_pages

    eng = srv.engine
    page_len = int(payload["page_len"])
    if page_len != eng.page_len:
        raise ValueError(
            f"page_len mismatch: shipped {page_len}, pool {eng.page_len}")
    keys = [(int(j), bytes.fromhex(d)) for j, d in payload["keys"]]
    L, _, Hkv, _, Dh = eng.cache.k.shape
    shape = tuple(int(x) for x in payload["shape"])
    want = (L, len(keys), Hkv, page_len, Dh)
    if shape != want:
        raise ValueError(f"page geometry mismatch: shipped {shape}, want {want}")
    dtype = _np_dtype(str(payload["dtype"]))
    pool_dtype = np.dtype(str(eng.cache.k.dtype))
    if dtype != pool_dtype:
        raise ValueError(f"dtype mismatch: shipped {dtype}, pool {pool_dtype}")
    raw_k = np.frombuffer(base64.b64decode(payload["k"]), dtype=dtype)
    raw_v = np.frombuffer(base64.b64decode(payload["v"]), dtype=dtype)
    n_elems = 1
    for x in shape:
        n_elems *= x
    if raw_k.size != n_elems or raw_v.size != n_elems:
        raise ValueError("payload size does not match declared shape")
    raw_k = raw_k.reshape(shape)
    raw_v = raw_v.reshape(shape)
    alloc = eng.allocator
    fresh = [i for i, key in enumerate(keys) if not alloc.has_key(key)]
    have = len(keys) - len(fresh)
    # adoption is pure opportunity: never evict this replica's own warm
    # reuse pool to make room for shipped pages — cap at what's free
    fresh = fresh[:max(alloc.available(), 0)]
    if not fresh:
        return 0, have
    pages = alloc.alloc(len(fresh))
    vk = jnp.asarray(np.ascontiguousarray(raw_k[:, fresh]))
    vv = jnp.asarray(np.ascontiguousarray(raw_v[:, fresh]))
    eng.cache = scatter_pages(eng.cache, jnp.asarray(pages, jnp.int32),
                              vk, vv, n=len(fresh))
    for p, i in zip(pages, fresh):
        alloc.register(p, keys[i])
        alloc.release(p)  # ref 0 + registered → reusable AND matchable
    srv.kv_handoff_adopted += len(fresh)
    _KV_HANDOFF.inc(len(fresh), side="adopted")
    return len(fresh), have


def ship_pages(decode_url: str, exported: dict,
               timeout_s: float = 30.0) -> tuple[int, int]:
    """POST an export payload to a decode replica's ``/v1/kv/adopt``.
    Returns ``(adopted, already_resident)``; raises on transport/HTTP
    failure (the caller degrades to a decode-side recompute)."""
    parts = urlsplit(decode_url)
    conn = http.client.HTTPConnection(parts.hostname, parts.port,
                                      timeout=timeout_s)
    try:
        body = json.dumps(exported).encode()
        conn.request("POST", "/v1/kv/adopt", body,
                     {"Content-Type": "application/json"})
        resp = conn.getresponse()
        data = resp.read()
        if resp.status != 200:
            raise RuntimeError(
                f"adopt refused: HTTP {resp.status}: {data[:200]!r}")
        obj = json.loads(data or b"{}")
        return int(obj.get("adopted") or 0), int(obj.get("already_resident") or 0)
    finally:
        conn.close()


def _np_dtype(name: str):
    """Resolve a wire dtype name, including the ml_dtypes extended set
    (bfloat16 et al.) numpy alone does not know."""
    import numpy as np

    try:
        return np.dtype(name)
    except TypeError:
        import ml_dtypes

        return np.dtype(getattr(ml_dtypes, name))


# =========================================================================
# Router-side coordinator: the prefill leg
# =========================================================================

class DisaggCoordinator:
    """Fires the prefill leg of a disaggregated request.

    Holds the prefill tier's own :class:`HealthMonitor` (jobtype
    ``prefill``) and picks least-outstanding exactly like the router's
    decode pick. ``prefill()`` is strictly best-effort — every failure path
    returns None and the decode replica recomputes the prompt; the client
    never sees the difference beyond TTFT."""

    def __init__(self, prefill_health: HealthMonitor,
                 timeout_s: float = 30.0, window: int = 512):
        self.health = prefill_health
        self.timeout_s = float(timeout_s)
        self._lock = threading.Lock()
        self._lat_ms: "deque[float]" = deque(maxlen=max(int(window), 1))

    def pick(self) -> Replica | None:
        snap = self.health.snapshot()
        for state in (ReplicaState.HEALTHY, ReplicaState.UNKNOWN):
            cands = [r for r in snap if r.state == state]
            if cands:
                return min(cands, key=lambda r: (r.outstanding, r.index))
        return None

    def prefill(self, prompt_tokens: list[int], decode_url: str,
                rid: str = "") -> dict | None:
        replica = self.pick()
        if replica is None:
            _PREFILL_LEGS.inc(outcome="no_replica")
            return None
        body = json.dumps({
            "prompt_tokens": prompt_tokens,
            "decode_url": decode_url,
            "timeout_s": self.timeout_s,
        }).encode()
        headers = {"Content-Type": "application/json"}
        if rid:
            headers["X-Tony-Request-Id"] = rid
        parts = urlsplit(replica.url)
        with self.health.lock:
            replica.outstanding += 1
        t0 = time.perf_counter()
        try:
            conn = http.client.HTTPConnection(parts.hostname, parts.port,
                                              timeout=self.timeout_s)
            try:
                conn.request("POST", "/v1/prefill", body, headers)
                resp = conn.getresponse()
                payload = resp.read()
            finally:
                conn.close()
        except (ConnectionError, OSError) as e:
            self.health.report_failure(replica, hard=True)
            _PREFILL_LEGS.inc(outcome="error")
            obs_trace.add_event("disagg.prefill_failed",
                                replica=replica.index, reason=str(e)[:200])
            return None
        finally:
            with self.health.lock:
                replica.outstanding -= 1
        if resp.status != 200:
            # 409 (dense engine) / 429 (overloaded) are the replica working
            # as designed — refuse the leg without marking it unhealthy;
            # only 5xx is a replica failure
            if resp.status >= 500:
                self.health.report_failure(replica, hard=False)
                _PREFILL_LEGS.inc(outcome="error")
            else:
                _PREFILL_LEGS.inc(outcome="refused")
            return None
        self.health.report_success(replica)
        took_ms = (time.perf_counter() - t0) * 1000.0
        with self._lock:
            self._lat_ms.append(took_ms)
        _PREFILL_LEGS.inc(outcome="ok")
        try:
            return json.loads(payload or b"{}")
        except ValueError:
            return None

    def stats(self) -> dict[str, Any]:
        with self._lock:
            xs = sorted(self._lat_ms)

        def pct(p: float) -> float | None:
            if not xs:
                return None
            return round(xs[min(int(len(xs) * p), len(xs) - 1)], 3)

        return {
            "legs_ok": _PREFILL_LEGS.value(outcome="ok"),
            "legs_refused": _PREFILL_LEGS.value(outcome="refused"),
            "legs_error": _PREFILL_LEGS.value(outcome="error"),
            "legs_no_replica": _PREFILL_LEGS.value(outcome="no_replica"),
            "handoff_p50_ms": pct(0.50),
            "handoff_p95_ms": pct(0.95),
        }


# =========================================================================
# Router tier sharding
# =========================================================================

def _hash64(s: str) -> int:
    return int.from_bytes(hashlib.sha256(s.encode()).digest()[:8], "big")


class ShardRing:
    """Consistent hash ring over router-shard indices with virtual nodes.

    ``assign`` is a pure function of (key, ring geometry, live set): every
    front replica — and a restarted front — resolves the same session to
    the same shard, which is what lets pins survive front failover without
    a shared store. A shard leaving moves only the sessions on its arcs
    (~1/N of the space), not a full rehash."""

    def __init__(self, shards: int, vnodes: int = 64):
        self.shards = int(shards)
        self.vnodes = max(int(vnodes), 1)
        pts = sorted((_hash64(f"shard-{s}:vn-{v}"), s)
                     for s in range(self.shards) for v in range(self.vnodes))
        self._points = pts
        self._hashes = [h for h, _ in pts]

    def assign(self, key: str, live: "set[int] | None" = None) -> int | None:
        """First live shard clockwise of ``key``'s point, or None when no
        shard is live. ``live=None`` means all shards."""
        if not self._points or (live is not None and not live):
            return None
        i = bisect.bisect_right(self._hashes, _hash64(key)) % len(self._points)
        seen: set[int] = set()
        for step in range(len(self._points)):
            s = self._points[(i + step) % len(self._points)][1]
            if live is None or s in live:
                return s
            seen.add(s)
            if len(seen) == self.shards:
                break
        return None


class RouterShardFront:
    """One HTTP front over N in-process :class:`FleetRouter` shards.

    Sessionful requests (``X-Tony-Session``) resolve to a shard by
    consistent hash over the LIVE shard set; sessionless ones round-robin.
    A shard connection failure marks it down, re-resolves the session on
    the ring, and counts exactly one re-pin for it through the same
    ``tony_router_session_repins_total`` the in-table move uses — the new
    shard's table has no pin, so the session's next turn pays one cold
    routing decision, which is precisely what that counter prices.

    A housekeeping thread doubles as the gossip-on-stats channel: each tick
    it (a) probes down shards back to life and (b) merges every live
    shard's prefix-hint snapshot into the others, so shared-system-prompt
    steering works no matter which shard admits the session."""

    def __init__(self, routers: list, port: int = 0, host: str = "127.0.0.1",
                 vnodes: int = 64, max_assignments: int = 100_000,
                 gossip_interval_s: float = 2.0,
                 connect_timeout_s: float = 5.0,
                 relay_timeout_s: float = 300.0):
        if not routers:
            raise ValueError("RouterShardFront needs at least one router")
        self.routers = list(routers)
        self.ring = ShardRing(len(self.routers), vnodes=vnodes)
        self.max_assignments = max(int(max_assignments), 1)
        self.gossip_interval_s = float(gossip_interval_s)
        self.connect_timeout_s = connect_timeout_s
        self.relay_timeout_s = relay_timeout_s
        self.started_s = time.time()
        self._lock = threading.Lock()
        self._assigned: "OrderedDict[str, int]" = OrderedDict()
        self._down: set[int] = set()
        self._rr = itertools.count()
        self._stop = threading.Event()
        front = self

        class Handler(BaseHTTPRequestHandler):
            def log_message(self, *a) -> None:  # quiet
                pass

            def do_GET(self) -> None:  # noqa: N802
                front._handle_get(self)

            def do_POST(self) -> None:  # noqa: N802
                front._handle_post(self)

        self._httpd = ThreadingHTTPServer((host, port), Handler)
        self._thread = threading.Thread(
            target=self._httpd.serve_forever, name="router-shard-front",
            daemon=True)
        self._gossip_thread = threading.Thread(
            target=self._housekeeping_loop, name="router-shard-gossip",
            daemon=True)

    # ------------------------------------------------------------ lifecycle
    @property
    def url(self) -> str:
        host, port = self._httpd.server_address[:2]
        return f"http://{host}:{port}"

    def start(self) -> "RouterShardFront":
        self._thread.start()
        if self.gossip_interval_s > 0:
            self._gossip_thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        self._httpd.shutdown()
        self._httpd.server_close()

    # ------------------------------------------------------------ resolution
    def live_shards(self) -> set[int]:
        with self._lock:
            return set(range(len(self.routers))) - self._down

    def _mark_down(self, shard: int) -> None:
        with self._lock:
            self._down.add(shard)

    def _resolve(self, session_id: str | None) -> int | None:
        """Shard for this request. Sessionful: sticky assignment while its
        shard lives, ring re-resolution (counted once) when it died."""
        live = self.live_shards()
        if not live:
            return None
        if not session_id:
            # sessionless: cheap spread; any live shard is equally right
            order = sorted(live)
            return order[next(self._rr) % len(order)]
        with self._lock:
            prior = self._assigned.get(session_id)
            if prior is not None and prior not in self._down:
                self._assigned.move_to_end(session_id)
                return prior
        shard = self.ring.assign(session_id, live)
        if shard is None:
            return None
        with self._lock:
            prior = self._assigned.get(session_id)
            if prior is not None and prior != shard and prior in self._down:
                # the session's pin died with its shard: exactly one re-pin
                # per failover — the fast path above short-circuits before
                # the ring once the new assignment is recorded
                sessions_mod.record_repin()
                obs_trace.add_event("router.shard_repin", session=session_id,
                                    old=prior, new=shard)
            self._assigned[session_id] = shard
            self._assigned.move_to_end(session_id)
            while len(self._assigned) > self.max_assignments:
                self._assigned.popitem(last=False)
        return shard

    # --------------------------------------------------------------- proxy
    def _handle_post(self, h: BaseHTTPRequestHandler) -> None:
        length = int(h.headers.get("Content-Length") or 0)
        body = h.rfile.read(length) if length else b""
        session_id = (h.headers.get("X-Tony-Session") or "").strip() or None
        fwd = {k: v for k, v in h.headers.items()
               if k.lower() in ("content-type", "x-tony-session",
                                "x-tony-request-id")}
        attempts = 0
        while attempts <= len(self.routers):
            attempts += 1
            shard = self._resolve(session_id)
            if shard is None:
                _reply_json_front(h, 503, {"error": "no live router shard"})
                return
            try:
                self._relay_to_shard(h, shard, h.path, body, fwd)
                return
            except _ShardDown:
                _SHARD_FAILOVERS.inc()
                self._mark_down(shard)
                continue
        _reply_json_front(h, 502, {"error": "router shards failing"})

    def _relay_to_shard(self, h: BaseHTTPRequestHandler, shard: int,
                        path: str, body: bytes, fwd: dict) -> None:
        """Relay one request to a shard's own HTTP server, streaming SSE
        through. Raises :class:`_ShardDown` only while no response byte has
        reached the client — after that a shard death truncates the stream,
        same contract as the router's own replica relay."""
        router = self.routers[shard]
        parts = urlsplit(router.url)
        try:
            conn = http.client.HTTPConnection(
                parts.hostname, parts.port, timeout=self.connect_timeout_s)
            conn.connect()
            conn.sock.settimeout(self.relay_timeout_s)
            conn.request("POST", path, body, fwd)
            resp = conn.getresponse()
        except (ConnectionError, OSError) as e:
            raise _ShardDown(str(e)) from e
        try:
            ctype = resp.headers.get("Content-Type") or ""
            if not ctype.startswith("text/event-stream"):
                try:
                    payload = resp.read()
                except (ConnectionError, OSError) as e:
                    raise _ShardDown(str(e)) from e
                h.send_response(resp.status)
                for k in ("Content-Type", "Retry-After", "X-Tony-Replica",
                          "X-Tony-Request-Id"):
                    if resp.headers.get(k):
                        h.send_header(k, resp.headers[k])
                h.send_header("X-Tony-Shard", str(shard))
                h.send_header("Content-Length", str(len(payload)))
                h.end_headers()
                h.wfile.write(payload)
                return
            h.send_response(200)
            h.send_header("Content-Type", ctype)
            h.send_header("Cache-Control", "no-cache")
            h.send_header("X-Tony-Shard", str(shard))
            for k in ("X-Tony-Replica", "X-Tony-Request-Id"):
                if resp.headers.get(k):
                    h.send_header(k, resp.headers[k])
            h.end_headers()
            while True:
                try:
                    chunk = resp.read1(8192)
                except (ConnectionError, OSError):
                    return  # truncation: the client sees the closed stream
                if not chunk:
                    return
                try:
                    h.wfile.write(chunk)
                    h.wfile.flush()
                except OSError:
                    return  # client went away
        finally:
            conn.close()

    # ----------------------------------------------------------- GET pages
    def _handle_get(self, h: BaseHTTPRequestHandler) -> None:
        if h.path == "/healthz":
            live = self.live_shards()
            _reply_json_front(h, 200 if live else 503, {
                "ok": bool(live),
                "shards": len(self.routers),
                "shards_live": len(live),
            })
        elif h.path == "/stats":
            _reply_json_front(h, 200, self.stats())
        else:
            _reply_json_front(h, 404, {"error": "not found"})

    def stats(self) -> dict[str, Any]:
        """Front + per-shard view. Router-level counters are process-global
        (every in-process shard reads the same registry series), so the
        front reports them ONCE from a live shard instead of summing N
        copies; only per-table figures (sessions) sum across shards."""
        live = self.live_shards()
        base: dict[str, Any] = {}
        for i in sorted(live):
            try:
                base = self.routers[i].stats()
                break
            except Exception:  # noqa: BLE001 — shard died under us
                continue
        shards = []
        total_sessions = 0
        for i, r in enumerate(self.routers):
            n = len(r.sessions)
            if i in live:
                total_sessions += n
            shards.append({"shard": i, "live": i in live, "url": r.url,
                           "sessions": n})
        router = dict(base.get("router") or {})
        router["sessions"] = total_sessions
        with self._lock:
            assigned = len(self._assigned)
        out = {
            "front": {
                "uptime_s": round(time.time() - self.started_s, 1),
                "shards": len(self.routers),
                "shards_live": len(live),
                "assigned_sessions": assigned,
                "shard_failovers": _SHARD_FAILOVERS.value(),
            },
            "router": router,
            "fleet": base.get("fleet") or {},
            "replicas": base.get("replicas") or [],
            "shards": shards,
        }
        if "disagg" in base:
            out["disagg"] = base["disagg"]
        return out

    # ------------------------------------------------- gossip/housekeeping
    def _housekeeping_loop(self) -> None:
        while not self._stop.wait(self.gossip_interval_s):
            try:
                self._probe_down_shards()
                self.gossip_hints()
            except Exception:  # noqa: BLE001 — housekeeping must never die
                pass

    def _probe_down_shards(self) -> None:
        with self._lock:
            down = list(self._down)
        for shard in down:
            parts = urlsplit(self.routers[shard].url)
            try:
                conn = http.client.HTTPConnection(
                    parts.hostname, parts.port, timeout=self.connect_timeout_s)
                try:
                    conn.request("GET", "/healthz")
                    conn.getresponse().read()
                finally:
                    conn.close()
            except (ConnectionError, OSError):
                continue
            with self._lock:
                self._down.discard(shard)

    def gossip_hints(self) -> int:
        """Merge every live shard's prefix-hint snapshot into the others
        (the gossip-on-stats channel). Returns the number of hints
        replicated this tick."""
        live = sorted(self.live_shards())
        merged: dict[str, int] = {}
        for i in live:
            merged.update(self.routers[i].sessions.export_hints())
        moved = 0
        for i in live:
            moved += self.routers[i].sessions.merge_hints(merged)
        return moved


class _ShardDown(Exception):
    """Shard-level connection failure (retryable on another shard)."""


def _reply_json_front(h: BaseHTTPRequestHandler, status: int, obj: Any) -> None:
    body = json.dumps(obj).encode()
    h.send_response(status)
    h.send_header("Content-Type", "application/json")
    h.send_header("Content-Length", str(len(body)))
    h.end_headers()
    h.wfile.write(body)
