"""Replica autoscaler: engine load signals → the AM's elastic-resize lever.

Runs next to the fleet router in the submitting process, sampling the
:class:`~tony_tpu.serve.health.HealthMonitor`'s aggregated ``/stats`` view
every ``tony.serve.autoscale-interval-ms``:

- **scale up** (+1) when the mean admission-queue depth per healthy replica
  exceeds ``scale-up-queue-depth`` OR fleet slot utilization exceeds
  ``scale-up-utilization``, sustained for ``scale-up-ticks`` samples;
- **scale down** (−1) when the fleet queue is empty AND utilization is below
  ``scale-down-utilization``, sustained for ``scale-down-ticks`` samples
  (longer than up: adding capacity is cheap, removing it costs a rebuild);
- clamped to [``min-replicas``, ``max-replicas``]; no decision while the
  fleet is mid-restart (zero healthy replicas says nothing about load);
- **SLO-aware** when a ``burn`` supplier is wired (tony.slo.*): a serve
  fast-burn rate >= 1.0 counts as up-pressure and vetoes scale-down — the
  fleet grows while the error budget is draining, not after the page.

Decisions call the AM's ``resize_jobtype`` RPC — the same rebuild path
capacity-loss downsizing uses — never a re-submission, so queue placement,
history, and the trace all stay with the one application. The current
replica count is re-read from the health monitor's fleet view each tick, so
an AM-side resize from another cause (capacity loss) reconverges instead of
fighting the autoscaler's stale notion of "current".

Scale-down is **drain-aware** when a ``drain`` lever is wired (the AM's
``request_task_drain`` RPC): before ``resize_jobtype`` removes the victim —
the highest-index replica, the one a shrink retires — the autoscaler asks it
to drain over the same heartbeat/DrainCourier contract pool preemption uses.
The replica stops admitting (the HealthMonitor flips it DRAINING, routing
sheds it, the SessionTable re-pins its sessions), finishes in-flight
streams, and acks; only then (or at ``scale-down-drain-ms``) does the resize
fire. An in-flight victim drain always carries through to its resize — the
drain is irreversible at the replica (stop-admit is terminal) and the AM
re-sends an un-acked notice every heartbeat, so abandoning it would strand
one permanently-DRAINING replica; pressure returning mid-drain simply scales
back up through the ordinary path after the (bounded) shrink completes.
"""

from __future__ import annotations

import threading
import time
from dataclasses import dataclass
from typing import Any, Callable

from tony_tpu.obs import logging as obs_logging
from tony_tpu.obs import metrics as obs_metrics
from tony_tpu.obs import trace as obs_trace
from tony_tpu.serve.health import FleetSignals, HealthMonitor, ReplicaState

_DECISIONS = obs_metrics.counter(
    "tony_serve_autoscale_decisions_total",
    "autoscaler resize decisions by direction", labelnames=("direction",))
_TARGET = obs_metrics.gauge(
    "tony_serve_target_replicas", "autoscaler's current replica target")
_DOWN_DRAINS = obs_metrics.counter(
    "tony_serve_scale_down_drains_total",
    "scale-down victim drains by how they resolved "
    "(drained / timeout / superseded)", labelnames=("outcome",))
_DEFICIT = obs_metrics.gauge(
    "tony_serve_replica_deficit",
    "replicas the autoscaler wants but the fleet has not placed — the "
    "deficit the AM publishes to the pool's capacity market")


@dataclass
class AutoscalePolicy:
    """Pure decision parameters (tony.serve.* keys)."""

    min_replicas: int = 1
    max_replicas: int = 1
    scale_up_queue_depth: float = 4.0
    scale_up_utilization: float = 0.85
    scale_down_utilization: float = 0.25
    scale_up_ticks: int = 2
    scale_down_ticks: int = 6
    #: paged-KV occupancy (live/total pages) above which the tier scales up
    #: — the decode tier's memory-bound signal in a disaggregated fleet,
    #: where slots can look idle while the page pool is the real ceiling.
    #: 0 disables (dense fleets report occupancy 0.0 anyway).
    scale_up_kv_occupancy: float = 0.0


class Autoscaler:
    """Threaded driver over a pure :meth:`decide` core.

    ``resize(job_name, instances)`` is the AM lever (tests inject a fake);
    production passes ``lambda job, n: rpc.call("resize_jobtype",
    job_name=job, instances=n)``.
    """

    def __init__(
        self,
        health: HealthMonitor,
        resize: Callable[[str, int], Any],
        policy: AutoscalePolicy,
        job_name: str = "serve",
        interval_s: float = 5.0,
        drain: Callable[[str, int], Any] | None = None,
        drain_timeout_s: float = 10.0,
        burn: Callable[[], float | None] | None = None,
    ):
        self.health = health
        self._resize = resize
        #: SLO fast-burn supplier (the AM's get_slo RPC distilled to the
        #: worst serve-objective fast burn, or None for no data). A burn
        #: >= 1.0 means the error budget drains faster than the compliance
        #: window sustains — counted as up-pressure alongside queue depth
        #: and utilization, so the fleet grows BEFORE the page fires rather
        #: than after the budget is gone. Optional: None keeps the classic
        #: load-only policy.
        self._burn = burn
        #: drain(job_name, index) → {"drained": bool, ...} — the AM's
        #: request_task_drain lever (idempotent poll). None → legacy abrupt
        #: scale-down (resize without draining the victim first).
        self._drain = drain
        self.drain_timeout_s = drain_timeout_s
        self.policy = policy
        self.job_name = job_name
        self.interval_s = interval_s
        self._up_ticks = 0
        self._down_ticks = 0
        self.target: int | None = None  # last requested target (None: no request yet)
        #: in-flight drain-then-shrink: {"victim", "target", "deadline"}
        self.pending_down: dict[str, Any] | None = None
        self._stop = threading.Event()
        self._thread = threading.Thread(
            target=self._loop, name="serve-autoscaler", daemon=True)

    def start(self) -> "Autoscaler":
        self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()

    def _loop(self) -> None:
        while not self._stop.wait(self.interval_s):
            try:
                self.tick()
            except Exception:  # noqa: BLE001 — AM restarting is routine here
                pass

    # ------------------------------------------------------------- decision
    def decide(self, current: int, sig: FleetSignals,
               burning: bool = False) -> int:
        """Next replica target given the fleet's load signals (and the SLO
        burn flag when a supplier is wired). Mutates the hysteresis tick
        counters; returns ``current`` for "hold"."""
        p = self.policy
        if sig.replicas_healthy == 0:
            # mid-restart / fleet down: no signal, no decision — and reset
            # hysteresis so stale pressure doesn't fire on the first sample
            # after recovery
            self._up_ticks = self._down_ticks = 0
            return current
        queue_per_replica = sig.queue_depth / sig.replicas_healthy
        want_up = (
            queue_per_replica > p.scale_up_queue_depth
            or sig.utilization > p.scale_up_utilization
            or (p.scale_up_kv_occupancy > 0
                and sig.kv_occupancy > p.scale_up_kv_occupancy)
            or burning
        )
        # a burning budget also vetoes scale-down: idle slots mean nothing
        # while the latency objective is missing — and so does a loaded KV
        # pool (idle slots + full pages = memory-bound, not idle)
        want_down = (sig.queue_depth == 0
                     and sig.utilization < p.scale_down_utilization
                     and not (p.scale_up_kv_occupancy > 0
                              and sig.kv_occupancy > p.scale_up_kv_occupancy)
                     and not burning)
        self._up_ticks = self._up_ticks + 1 if want_up else 0
        self._down_ticks = self._down_ticks + 1 if want_down else 0
        if self._up_ticks >= p.scale_up_ticks:
            self._up_ticks = 0
            return min(current + 1, max(p.max_replicas, p.min_replicas, 1))
        if self._down_ticks >= p.scale_down_ticks:
            self._down_ticks = 0
            return max(current - 1, max(p.min_replicas, 1))
        return current

    def deficit(self) -> int:
        """Replicas wanted but not yet placed: how far the fleet lags the
        last requested target. Nonzero while a scale-up waits on capacity —
        the quantity the AM's capacity-market publish mirrors pool-side."""
        if self.target is None:
            return 0
        return max(self.target - self.health.fleet_signals().replicas_known, 0)

    def tick(self) -> None:
        sig = self.health.fleet_signals()
        _DEFICIT.set(max((self.target or 0) - sig.replicas_known, 0))
        current = sig.replicas_known or (self.target or 0)
        if current == 0:
            return  # nothing resolved yet
        burning = False
        if self._burn is not None:
            try:
                b = self._burn()
                burning = b is not None and b >= 1.0
            except Exception:  # noqa: BLE001 — AM mid-exit: load signals still decide
                pass
        target = self.decide(current, sig, burning=burning)
        _TARGET.set(target)
        if self.pending_down is not None:
            # carry the shrink through even if pressure returned: the drain
            # request is already in flight (the AM re-sends an un-acked
            # notice every heartbeat, and a drained replica cannot un-drain
            # — EngineServer.stop is terminal), so "cancelling" here would
            # strand one permanently-DRAINING replica that still counts as
            # capacity. The window is bounded by drain_timeout_s; returning
            # pressure scales back up through the ordinary path right after
            # the rebuild.
            self._drive_pending_down(current)
            return
        if target == current:
            return
        direction = "up" if target > current else "down"
        _DECISIONS.inc(direction=direction)
        obs_trace.add_event(
            "autoscale.decision", direction=direction,
            current=current, target=target,
            queue_depth=sig.queue_depth, utilization=round(sig.utilization, 3),
            slo_burning=burning,
        )
        if direction == "down" and self._drain is not None:
            # drain-aware shrink: the resize retires the HIGHEST index —
            # ask exactly that replica to drain first, then shrink
            victim = current - 1
            self.pending_down = {
                "victim": victim, "target": target,
                "deadline": time.monotonic() + self.drain_timeout_s,
            }
            obs_trace.add_event(
                "autoscale.drain_victim", victim=victim, target=target)
            obs_logging.info(
                f"[tony-serve] scale-down to {target}: draining "
                f"{self.job_name}:{victim} before removal")
            self._drive_pending_down(current)
            return
        self._do_resize(target)

    def _drive_pending_down(self, current: int) -> None:
        """One poll of an in-flight drain-then-shrink: re-issue the
        (idempotent) drain request, and resize once the victim acked — or
        when it reads DRAINING in the fleet view (belt for replicas that
        stop admitting but keep streams open past this poll), or at the
        drain deadline (a wedged victim must not pin capacity forever)."""
        pd = self.pending_down
        assert pd is not None
        if current <= pd["target"]:
            # another actor (capacity loss, tony resize) already shrank past
            # our target: nothing left to do
            _DOWN_DRAINS.inc(outcome="superseded")
            self.pending_down = None
            return
        drained = False
        try:
            resp = self._drain(self.job_name, pd["victim"])
            drained = bool(resp and resp.get("drained"))
        except Exception as e:  # noqa: BLE001 — transport churn: retry next tick
            obs_logging.warning(
                f"[tony-serve] drain poll for {self.job_name}:{pd['victim']} "
                f"failed ({e}); retrying")
        if not drained:
            for r in self.health.snapshot():
                if r.index == pd["victim"] and r.state in (
                    ReplicaState.DRAINING, ReplicaState.DOWN
                ):
                    # stopped admitting (or already exited post-drain):
                    # routing has shed it, sessions re-pinned
                    drained = True
                    break
        timed_out = time.monotonic() >= pd["deadline"]
        if not drained and not timed_out:
            return  # keep waiting; poll again next tick
        if drained:
            _DOWN_DRAINS.inc(outcome="drained")
        else:
            _DOWN_DRAINS.inc(outcome="timeout")
            obs_logging.warning(
                f"[tony-serve] drain of {self.job_name}:{pd['victim']} timed "
                f"out after {self.drain_timeout_s:.0f}s — resizing anyway")
        self.pending_down = None
        self._do_resize(pd["target"])

    def _do_resize(self, target: int) -> None:
        try:
            self._resize(self.job_name, target)
        except Exception as e:  # noqa: BLE001 — typed rejection vs transport churn
            if "InvalidResizeError" in str(e):
                # the AM's typed verdict (out of tony.elastic.* bounds, or a
                # resize is already pending): surface it and hold the old
                # target — re-deciding next tick is correct either way
                obs_logging.warning(
                    f"[tony-serve] autoscaler resize {self.job_name}→{target} "
                    f"rejected: {e}")
                return
            raise  # transport failure: the loop's catch-all retries next tick
        self.target = target
