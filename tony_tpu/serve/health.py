"""Replica discovery + health for the serving fleet.

The fleet's membership truth is the AM (§3.4 URL registration: every
``serve`` replica registers its endpoint through ``register_task_url``, the
same path ``tony serve`` used for its single task). :class:`HealthMonitor`
polls ``get_task_infos`` to discover/refresh replica endpoints — so a gang
restart (new URLs, bumped ``restart_attempt``) re-resolves automatically —
and layers a per-replica health state machine on top:

    UNKNOWN ──probe ok──▶ HEALTHY ──/stats draining──▶ DRAINING
       ▲                    │  ▲                          │
       └──new attempt──┐    │  └──probe recovers──┐       │
                       ▼    ▼                     │       ▼
                      DOWN ◀──────────────────────┴── (probe fails)

- **active**: every tick, GET each replica's ``/stats`` (the engine server's
  counters endpoint). ``healthy: false`` (fatal engine error) → DOWN
  immediately; connection failures → DOWN after ``fail_threshold``
  consecutive misses; ``draining: true`` (SIGTERM received) → DRAINING.
- **passive**: the router reports request-level failures
  (:meth:`HealthMonitor.report_failure`) which count against the same
  threshold, and successes (:meth:`report_success`) which reset it — a
  replica that silently blackholes requests goes DOWN between probes.

DOWN and DRAINING replicas take no new requests; a successful probe (the
restarted replica came back) returns them to HEALTHY. The monitor also
aggregates the autoscaler's input signals (queue depth, slot utilization)
from the same ``/stats`` payloads — one poll feeds routing, scaling, and
the ``/fleet`` status page.
"""

from __future__ import annotations

import enum
import json
import threading
import time
import urllib.request
from dataclasses import dataclass, field
from typing import Any, Callable

from tony_tpu.obs import metrics as obs_metrics

_REPLICAS = obs_metrics.gauge(
    "tony_router_replicas", "fleet replicas by health state", labelnames=("state",))
_RESOLVES = obs_metrics.counter(
    "tony_router_endpoint_resolves_total",
    "replica endpoint (re-)resolutions from the AM's task registry")


class ReplicaState(enum.Enum):
    UNKNOWN = "UNKNOWN"      # endpoint known, no probe verdict yet
    HEALTHY = "HEALTHY"
    DRAINING = "DRAINING"    # engine refusing admissions (SIGTERM drain)
    DOWN = "DOWN"

    @property
    def routable(self) -> bool:
        """May the router send NEW requests here? UNKNOWN is optimistically
        routable only as a last resort (see FleetRouter._pick)."""
        return self == ReplicaState.HEALTHY


@dataclass
class Replica:
    """One serve task's endpoint + health view."""

    index: int
    url: str                              # "http://host:port"
    attempt: int = 0                      # gang epoch the URL registered in
    state: ReplicaState = ReplicaState.UNKNOWN
    failures: int = 0                     # consecutive probe/request failures
    outstanding: int = 0                  # in-flight router requests (router-maintained)
    stats: dict[str, Any] = field(default_factory=dict)  # last /stats payload
    last_probe_ms: float = 0.0

    @property
    def id(self) -> str:
        return f"serve:{self.index}"

    def to_info(self) -> dict[str, Any]:
        return {
            "index": self.index,
            "url": self.url,
            "attempt": self.attempt,
            "state": self.state.value,
            "failures": self.failures,
            "outstanding": self.outstanding,
            "queue_depth": self.stats.get("queue_depth"),
            "slots_active": self.stats.get("slots_active"),
            "slots_total": self.stats.get("slots_total"),
        }


@dataclass
class FleetSignals:
    """Aggregated autoscaler inputs (healthy replicas only)."""

    replicas_known: int = 0
    replicas_healthy: int = 0
    queue_depth: int = 0      # summed engine admission+staging queues
    slots_active: int = 0
    slots_total: int = 0
    pages_live: int = 0       # paged engines only: referenced KV pages
    pages_total: int = 0      # paged engines only: pool capacity

    @property
    def utilization(self) -> float:
        return self.slots_active / self.slots_total if self.slots_total else 0.0

    @property
    def kv_occupancy(self) -> float:
        """Live-page fraction of the fleet's paged-KV pools (0.0 on dense
        fleets) — the decode tier's memory-bound scaling signal."""
        return self.pages_live / self.pages_total if self.pages_total else 0.0


class HealthMonitor:
    """Background discovery + health loop over one job's serve replicas.

    ``am_call(method, **params)`` is the AM RPC surface (tests inject a
    fake); probing uses plain HTTP against each replica's ``/stats``.
    """

    def __init__(
        self,
        am_call: Callable[..., Any],
        job_name: str = "serve",
        interval_s: float = 1.0,
        fail_threshold: int = 3,
        probe_timeout_s: float = 2.0,
    ):
        self._am_call = am_call
        self.job_name = job_name
        self.interval_s = interval_s
        self.fail_threshold = max(int(fail_threshold), 1)
        self.probe_timeout_s = probe_timeout_s
        self.lock = threading.Lock()
        self.replicas: dict[int, Replica] = {}
        self.restart_attempt = 0
        self._stop = threading.Event()
        self._thread = threading.Thread(target=self._loop, name="fleet-health", daemon=True)

    # ------------------------------------------------------------ lifecycle
    def start(self) -> "HealthMonitor":
        self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()

    def tick(self) -> None:
        """One resolve+probe pass (the loop body; tests drive it directly)."""
        self._resolve()
        for replica in self.snapshot():
            self._probe(replica)
        self._export_gauges()

    def _loop(self) -> None:
        while not self._stop.wait(self.interval_s):
            try:
                self.tick()
            except Exception:  # noqa: BLE001 — health must outlive AM blips
                pass

    # ------------------------------------------------------------ discovery
    def _resolve(self) -> None:
        """Refresh endpoints from the AM. A bumped ``restart_attempt`` (gang
        restart) invalidates every known URL — the old processes are dead
        even if their ports answer; replicas go DOWN until the new epoch's
        registrations arrive. Indices that vanished (scale-down) drop."""
        try:
            status = self._am_call("get_application_status")
            infos = self._am_call("get_task_infos")
        except Exception:  # noqa: BLE001 — AM restarting / unreachable
            return
        attempt = int(status.get("restart_attempt") or 0)
        present: set[int] = set()
        seen: dict[int, str] = {}
        for info in infos:
            if info.get("name") != self.job_name:
                continue
            idx = int(info["index"])
            present.add(idx)  # the current session HAS this task (any status)
            url = info.get("url")
            if url and info.get("status") not in ("FAILED", "KILLED", "LOST"):
                seen[idx] = url
        with self.lock:
            if attempt != self.restart_attempt:
                self.restart_attempt = attempt
                for r in self.replicas.values():
                    r.state = ReplicaState.DOWN  # stale epoch: URL is dead
            for idx in list(self.replicas):
                if idx not in present:
                    # resized away entirely (the session no longer declares
                    # the index); mid-restart tasks stay listed (status NEW),
                    # so an outage keeps its DOWN entry visible in /fleet
                    del self.replicas[idx]
            for idx, url in seen.items():
                r = self.replicas.get(idx)
                if r is None or r.url != url or r.attempt != attempt:
                    _RESOLVES.inc()
                    self.replicas[idx] = Replica(index=idx, url=url, attempt=attempt)

    # ------------------------------------------------------------- probing
    def _probe(self, replica: Replica) -> None:
        try:
            with urllib.request.urlopen(
                replica.url + "/stats", timeout=self.probe_timeout_s
            ) as resp:
                payload = json.loads(resp.read())
        except Exception:  # noqa: BLE001 — any transport/parse failure is a miss
            self._count_failure(replica)
            return
        with self.lock:
            replica.last_probe_ms = time.time() * 1000
            replica.stats = payload
            if replica.attempt != self.restart_attempt:
                # stale-epoch endpoint still answering inside the SIGTERM
                # window: its process is condemned — never flip it routable
                replica.state = ReplicaState.DOWN
            elif not payload.get("healthy", True):
                replica.state = ReplicaState.DOWN  # fatal engine error: no retry budget
                replica.failures = self.fail_threshold
            elif payload.get("draining"):
                replica.state = ReplicaState.DRAINING
                replica.failures = 0
            else:
                replica.state = ReplicaState.HEALTHY
                replica.failures = 0

    def _count_failure(self, replica: Replica) -> None:
        with self.lock:
            replica.failures += 1
            if replica.failures >= self.fail_threshold:
                replica.state = ReplicaState.DOWN

    # ----------------------------------------------------- passive marking
    def report_failure(self, replica: Replica, hard: bool = False) -> None:
        """Router-observed failure. ``hard`` (connection refused/reset — the
        process is gone) marks DOWN immediately; soft failures (5xx) count
        against the probe threshold."""
        if hard:
            with self.lock:
                replica.failures = max(replica.failures, self.fail_threshold)
                replica.state = ReplicaState.DOWN
        else:
            self._count_failure(replica)

    def report_draining(self, replica: Replica) -> None:
        """Router-observed drain refusal (503 "server is draining"): the
        replica is mid-lifecycle, not failing — shed it from routing NOW
        instead of waiting for the next active probe to flip it. The probe
        keeps owning recovery (a drained-then-restarted replica flips back
        HEALTHY the usual way)."""
        with self.lock:
            if replica.attempt == self.restart_attempt:
                replica.state = ReplicaState.DRAINING
                replica.failures = 0

    def report_success(self, replica: Replica) -> None:
        with self.lock:
            replica.failures = 0
            # never resurrect a stale-epoch replica: after a gang restart
            # bumps the attempt, a completing in-flight request on the OLD
            # (dying) endpoint must not flip it back to routable
            if (replica.state == ReplicaState.DOWN
                    and replica.attempt == self.restart_attempt):
                replica.state = ReplicaState.HEALTHY

    # ------------------------------------------------------------- queries
    def snapshot(self) -> list[Replica]:
        with self.lock:
            return sorted(self.replicas.values(), key=lambda r: r.index)

    def fleet_signals(self) -> FleetSignals:
        sig = FleetSignals()
        with self.lock:
            for r in self.replicas.values():
                sig.replicas_known += 1
                if r.state != ReplicaState.HEALTHY:
                    continue
                sig.replicas_healthy += 1
                st = r.stats
                sig.queue_depth += int(st.get("queue_depth") or 0)
                sig.slots_active += int(st.get("slots_active") or 0)
                sig.slots_total += int(st.get("slots_total") or 0)
                sig.pages_live += int(st.get("pages_live") or 0)
                sig.pages_total += int(st.get("pages_total") or 0)
        return sig

    def fleet_info(self) -> dict[str, Any]:
        with self.lock:
            return {
                "job": self.job_name,
                "restart_attempt": self.restart_attempt,
                "replicas": [r.to_info() for r in
                             sorted(self.replicas.values(), key=lambda r: r.index)],
            }

    def _export_gauges(self) -> None:
        counts = dict.fromkeys(ReplicaState, 0)
        for r in self.snapshot():
            counts[r.state] += 1
        for state, n in counts.items():
            _REPLICAS.set(n, state=state.value.lower())
