"""tony-tpu: a TPU-native distributed-ML orchestration + training framework.

A from-scratch rebuild of the capabilities of TonY (LinkedIn's "TensorFlow on
YARN" orchestrator — reference layout: tony-core/src/main/java/com/linkedin/tony/),
re-designed TPU-first:

- control plane: ``tony_tpu.cluster`` — Client / ApplicationMaster / TaskExecutor
  (analog of TonyClient.java / TonyApplicationMaster.java / TaskExecutor.java)
  gang-scheduling **TPU slices** instead of GPU-labeled YARN containers.
- runtime adapters: ``tony_tpu.runtime`` — analog of tony-core runtime/
  (TFRuntime/PyTorchRuntime/HorovodRuntime/MXNetRuntime), bootstrapping
  jax.distributed / TF_CONFIG / torch rendezvous env contracts.
- parallelism: ``tony_tpu.parallel`` — the layer TonY delegated to user
  frameworks, here first-class: mesh axes (data/fsdp/model/expert/context/stage),
  FSDP, tensor/pipeline/expert/context parallelism over XLA collectives on
  ICI/DCN.
- compute: ``tony_tpu.ops`` (Pallas TPU kernels + XLA references) and
  ``tony_tpu.models`` (MLP, BERT, ResNet, Llama, Mixtral).
- training: ``tony_tpu.train`` — train-step builder, Orbax checkpointing,
  MFU/throughput metrics.

See SURVEY.md at the repo root for the full blueprint and reference citations.
"""

__version__ = "0.1.0"

from tony_tpu import constants  # noqa: F401
from tony_tpu.config import TonyConfig, keys  # noqa: F401
