"""Fault-schedule grammar for the deterministic chaos layer.

A schedule (``tony.chaos.spec``) is a ``;``-separated list of fault entries:

    rpc-drop:p=0.05;exec-crash:worker:1@gang_complete;hb-stall:worker:0@t+5s;ckpt-corrupt:latest

Each entry is ``kind[:<job>:<index>][:k=v ...][:arg ...][@trigger]`` where

- ``kind`` is one of :data:`FAULT_KINDS`;
- ``<job>:<index>`` targets one task (``worker:1``); untargeted faults apply
  to any matching process (or, for container faults, every live container);
- ``k=v`` tokens are numeric parameters (``p`` = per-event probability,
  ``ms`` = duration);
- bare tokens are positional arguments (``ckpt-corrupt:latest``);
- ``@t+5s`` arms the fault 5 s after the injecting process starts;
  ``@step+4`` arms it once the job's reported TRAINING step reaches 4
  (AM-decided faults only: container faults and ``am-crash`` — the AM gates
  on the metrics the executors push, so a "preempt K workers mid-run" or
  "SIGKILL the AM mid-run" schedule fires against progress, not wall
  time); ``@gang_complete`` / ``@registered`` tie it to a lifecycle
  point instead.

Entries parse to :class:`FaultSpec` rows inside a :class:`FaultSchedule`
carrying the run's seed — the pair (spec string, seed) fully determines every
injection decision (see context.py), which is what makes a chaos run
reproducible.
"""

from __future__ import annotations

import re
from dataclasses import dataclass, field

from tony_tpu.config.config import parse_time_ms

#: Every fault kind the injection points understand.
FAULT_KINDS = frozenset({
    # cluster/rpc.py — client-side frame faults
    "rpc-drop",        # the frame never leaves: the call fails with ConnectionError
    "rpc-delay",       # the call is delayed by `ms` before being sent
    "rpc-blackhole",   # sent into the void: blocks ~the socket timeout, then times out
    "rpc-sever",       # connection closed after send, before the response arrives
    # cluster/executor.py — supervisor/child faults
    "exec-crash",      # the executor dies abruptly (container crash)
    "exec-hang",       # the child is SIGSTOPped (or the barrier wedges pre-child)
    "hb-stall",        # heartbeats stop while the process lives (wedged executor)
    "reg-slow",        # registration delayed by `ms`
    # cluster/resources.py + cluster/pool.py — container/pool faults
    "node-loss",       # every live container dies with EXIT_NODE_LOST
    "preempt",         # targeted containers die with EXIT_PREEMPTED (budget-exempt)
    "preempt-drain",   # a COOPERATIVE pool drain notice (checkpoint-then-yield
                       # machinery end to end, no pool service needed); ms= sets
                       # the synthesized deadline (default 20s)
    "capacity-flap",   # a capacity probe sees an empty pool (downsize hysteresis test)
    # cluster/appmaster.py + cluster/pool.py — CONTROL-PLANE faults
    "am-crash",        # the AM SIGKILLs itself (work-preserving takeover / AM-retry path)
    "pool-crash",      # the pool-service RM daemon SIGKILLs itself (journal recovery path)
    # train/checkpoint.py — artifact faults
    "ckpt-corrupt",    # the newest checkpoint is torn (truncated/garbled) before restore
})

#: Kinds whose target names the *victim container*, not the injecting process
#: (the AM applies them at the ResourceManager seam).
CONTAINER_FAULTS = frozenset({"node-loss", "preempt"})

#: Kinds that may gate on the job's reported training step (``@step+N``):
#: container faults, the cooperative drain notice, and the AM's own crash —
#: all decided in the AM, the only process fed the executors' pushed step
#: metrics.
STEP_GATED_FAULTS = CONTAINER_FAULTS | frozenset({"am-crash", "preempt-drain"})

_TARGET_JOB = re.compile(r"^[A-Za-z][A-Za-z0-9_\-]*$")


@dataclass
class FaultSpec:
    """One parsed fault entry."""

    kind: str
    target: tuple[str, int] | None = None  # (job_type, index); None = any
    trigger: str | None = None             # lifecycle point ("gang_complete", ...)
    delay_ms: int = 0                      # from "@t+5s": armed this long after process start
    step_gate: int = 0                     # from "@step+4": armed once the job reports this step
    args: tuple[str, ...] = ()             # positional tokens ("latest", ...)
    params: dict[str, float] = field(default_factory=dict)  # k=v tokens (p, ms, ...)
    entry: str = ""                        # the original entry text (canonical key)

    @property
    def key(self) -> str:
        """Stable identity used for once-per-job latches and injection logs."""
        return self.entry or self.kind

    def ms(self, default: int) -> int:
        """The `ms` duration parameter, defaulted."""
        v = self.params.get("ms")
        return int(v) if v is not None else default


def _parse_entry(entry: str) -> FaultSpec:
    text = entry.strip()
    body, trigger, delay_ms, step_gate = text, None, 0, 0
    at = text.rfind("@")
    if at != -1:
        body, trig = text[:at], text[at + 1:].strip()
        if trig.startswith("t+"):
            delay_ms = parse_time_ms(trig[2:])
        elif trig.startswith("step+"):
            try:
                step_gate = int(trig[5:])
            except ValueError:
                raise ValueError(f"non-integer step gate in fault entry {text!r}") from None
            if step_gate < 1:
                raise ValueError(f"step gate must be >= 1 in fault entry {text!r}")
        elif trig:
            trigger = trig
        else:
            raise ValueError(f"empty trigger in fault entry {text!r}")
    tokens = [t.strip() for t in body.split(":")]
    kind = tokens[0]
    if kind not in FAULT_KINDS:
        raise ValueError(
            f"unknown fault kind {kind!r} in {text!r} (known: {', '.join(sorted(FAULT_KINDS))})"
        )
    target: tuple[str, int] | None = None
    args: list[str] = []
    params: dict[str, float] = {}
    rest = tokens[1:]
    i = 0
    while i < len(rest):
        tok = rest[i]
        if "=" in tok:
            k, _, v = tok.partition("=")
            try:
                params[k] = float(v)
            except ValueError:
                raise ValueError(f"non-numeric parameter {tok!r} in fault entry {text!r}") from None
        elif (
            target is None
            and i + 1 < len(rest)
            and rest[i + 1].isdigit()
            and _TARGET_JOB.match(tok)
        ):
            target = (tok, int(rest[i + 1]))
            i += 1
        elif tok:
            args.append(tok)
        i += 1
    p = params.get("p")
    if p is not None and not 0 <= p <= 1:
        raise ValueError(f"probability p={p} out of [0, 1] in fault entry {text!r}")
    if step_gate and kind not in STEP_GATED_FAULTS:
        raise ValueError(
            f"@step+N gates are AM-decided faults only ({', '.join(sorted(STEP_GATED_FAULTS))}) "
            f"— only the AM sees the job's reported step — in fault entry {text!r}"
        )
    return FaultSpec(kind, target, trigger, delay_ms, step_gate, tuple(args), params, entry=text)


@dataclass
class FaultSchedule:
    """The parsed ``tony.chaos.spec`` plus the run seed."""

    faults: tuple[FaultSpec, ...]
    seed: int = 0
    spec: str = ""

    @classmethod
    def parse(cls, spec: str, seed: int = 0) -> "FaultSchedule":
        faults = tuple(
            _parse_entry(e) for e in (spec or "").split(";") if e.strip()
        )
        return cls(faults=faults, seed=seed, spec=spec or "")

    def of_kind(self, kind: str) -> tuple[FaultSpec, ...]:
        return tuple(f for f in self.faults if f.kind == kind)
