"""Artifact-level injections: checkpoint corruption.

``corrupt_latest_checkpoint`` tears the newest step of an Orbax checkpoint
directory the way a crash mid-write would (files truncated to zero, or
garbled with ``mode="garbage"``). ``maybe_corrupt_checkpoint`` is the
env-gated hook ``restore_or_init`` calls before its first restore: a no-op
unless the process carries a ``ckpt-corrupt`` fault, in which case the tear
happens exactly once per job (the chaos once-latch) and the hardened restore
path must fall back to the newest intact step.
"""

from __future__ import annotations

import os

from tony_tpu.chaos.context import ChaosContext


def _step_dirs(directory: str) -> list[int]:
    try:
        return sorted(int(name) for name in os.listdir(directory) if name.isdigit())
    except OSError:
        return []


def corrupt_latest_checkpoint(directory: str, mode: str = "truncate") -> int | None:
    """Tear every file of the newest step dir; returns the step, or None when
    there is nothing to corrupt."""
    steps = _step_dirs(directory)
    if not steps:
        return None
    step = steps[-1]
    root = os.path.join(directory, str(step))
    for dirpath, _, files in os.walk(root):
        for fn in files:
            path = os.path.join(dirpath, fn)
            try:
                if mode == "garbage":
                    with open(path, "wb") as fh:
                        fh.write(b"\xde\xad\xbe\xef")
                else:
                    with open(path, "wb"):
                        pass  # truncate to zero: a torn in-flight write
            except OSError:
                continue
    return step


def maybe_corrupt_checkpoint(directory: str) -> int | None:
    """The restore_or_init injection point. Fires the armed ``ckpt-corrupt``
    fault (env contract: TONY_CHAOS_SPEC/SEED) against ``directory`` when a
    checkpoint exists to corrupt; returns the torn step or None."""
    ctx = ChaosContext.from_env()
    if ctx is None:
        return None
    if not _step_dirs(directory):
        return None  # nothing to corrupt yet: don't spend the once-per-job latch
    f = ctx.take("ckpt-corrupt", detail={"directory": directory})
    if f is None:
        return None
    mode = f.args[1] if len(f.args) > 1 else "truncate"
    return corrupt_latest_checkpoint(directory, mode=mode)
