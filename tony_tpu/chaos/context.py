"""Per-process chaos runtime: seeded decisions, once-latches, injection log.

Every process in a chaos run (AM, each executor supervisor, each training
child) builds one :class:`ChaosContext` from the frozen config (control-plane
processes) or from the ``TONY_CHAOS_*`` env contract (the training child).
``from_config``/``from_env`` return ``None`` when no schedule is configured,
and every injection point guards on that — the production path pays one
``is None`` check and nothing else.

Determinism: each (seed, identity, kind) triple derives its own PRNG, so a
process's decision stream for a fault kind is a pure function of the run seed
and the order of its own queries — re-running the same schedule with the same
seed reproduces the same injected-fault sequence (asserted in
tests/test_chaos.py).

Once-semantics: probability faults (``p=``) draw on every query and never
latch. All other faults fire **once per job**, latched through a marker file
under ``<staging>/chaos/fired/`` so the latch survives gang restarts — an
``exec-crash`` must kill attempt 0, not every attempt forever.

Every injection is appended to ``<staging>/chaos/injections-<identity>.jsonl``
(and to the in-memory ``injected`` list) so ``tony chaos`` can report exactly
what a run suffered.
"""

from __future__ import annotations

import hashlib
import json
import os
import random
import threading
import time
from typing import Any, Mapping

from tony_tpu import constants
from tony_tpu.chaos.schedule import CONTAINER_FAULTS, FaultSchedule, FaultSpec
from tony_tpu.obs import metrics as obs_metrics
from tony_tpu.obs import trace as obs_trace

_INJECTIONS = obs_metrics.counter(
    "tony_chaos_injections_total", "chaos faults actually injected", labelnames=("kind",))


class ChaosContext:
    def __init__(self, schedule: FaultSchedule, identity: str, staging_dir: str | None = None):
        self.schedule = schedule
        self.identity = identity
        self.task = _parse_task(identity)
        self.injected: list[dict[str, Any]] = []
        self._staging = staging_dir
        self._started = time.monotonic()
        self._progress_step = 0  # highest training step reported (set_progress)
        self._lock = threading.Lock()
        self._rngs: dict[str, random.Random] = {}
        self._latched: set[str] = set()
        self._log_path: str | None = None
        if staging_dir:
            log_dir = os.path.join(staging_dir, "chaos")
            try:
                os.makedirs(log_dir, exist_ok=True)
                self._log_path = os.path.join(
                    log_dir, f"injections-{identity.replace(':', '_').replace(os.sep, '_')}.jsonl"
                )
            except OSError:
                self._log_path = None  # chaos logging is best-effort, never fatal

    # ------------------------------------------------------------ factories
    @classmethod
    def from_config(cls, config, identity: str, staging_dir: str | None = None) -> "ChaosContext | None":
        """Build from the frozen job config; None when chaos is not configured."""
        from tony_tpu.config import keys

        spec = config.get(keys.CHAOS_SPEC) or ""
        if not spec.strip():
            return None
        return cls(FaultSchedule.parse(spec, config.get_int(keys.CHAOS_SEED, 0)), identity, staging_dir)

    @classmethod
    def from_env(cls, env: Mapping[str, str] | None = None) -> "ChaosContext | None":
        """Build from the child-process env contract (TONY_CHAOS_SPEC/SEED)."""
        env = os.environ if env is None else env
        spec = env.get(constants.ENV_CHAOS_SPEC, "")
        if not spec.strip():
            return None
        try:
            seed = int(env.get(constants.ENV_CHAOS_SEED, "0") or 0)
        except ValueError:
            seed = 0
        job = env.get(constants.ENV_JOB_NAME)
        idx = env.get(constants.ENV_TASK_INDEX)
        identity = f"{job}:{idx}" if job and idx is not None else "proc"
        return cls(FaultSchedule.parse(spec, seed), identity, staging_dir=env.get(constants.ENV_STAGING_DIR) or None)

    # ------------------------------------------------------------- decisions
    def elapsed_ms(self) -> float:
        return (time.monotonic() - self._started) * 1000

    def set_progress(self, step: int) -> None:
        """Latest TRAINING step the job has reported (the AM feeds this from
        the executors' pushed metrics each monitor tick). ``@step+N``-gated
        faults stay unarmed until it reaches N — a "preempt K workers
        mid-run" schedule then fires against real progress (after the step-N
        checkpoint exists) instead of guessing a wall-clock delay. The step
        counter only moves forward: a gang restart resetting the reported
        step must not re-arm a gate that already opened."""
        with self._lock:
            if step > self._progress_step:
                self._progress_step = step

    def take(self, kind: str, trigger: str | None = None, detail: dict[str, Any] | None = None) -> FaultSpec | None:
        """The single decision gate: the first armed fault of ``kind`` at this
        lifecycle point, or None. A returned fault has been recorded (and, for
        non-probability faults, latched once-per-job) — apply it."""
        for f in self.schedule.faults:
            if f.kind != kind or f.trigger != trigger:
                continue
            got = self.take_spec(f, detail=detail)
            if got is not None:
                return got
        return None

    def take_spec(self, f: FaultSpec, detail: dict[str, Any] | None = None) -> FaultSpec | None:
        """Gate one specific fault: target match, time-arming, probability
        draw, once-latch. (Container-fault targets name the victim container,
        checked by the applier, not the injecting process.)"""
        if f.kind not in CONTAINER_FAULTS and f.target is not None and f.target != self.task:
            return None
        with self._lock:
            if f.delay_ms and self.elapsed_ms() < f.delay_ms:
                return None
            if f.step_gate and self._progress_step < f.step_gate:
                return None
            p = f.params.get("p")
            if p is not None:
                if self._rng_locked(f.kind).random() >= p:
                    return None
            else:
                if f.key in self._latched or not self._latch_global_locked(f):
                    return None
                self._latched.add(f.key)
            self._record_locked(f, detail)
            return f

    def _rng_locked(self, kind: str) -> random.Random:
        r = self._rngs.get(kind)
        if r is None:
            h = hashlib.sha256(f"{self.schedule.seed}:{self.identity}:{kind}".encode()).digest()
            r = self._rngs[kind] = random.Random(int.from_bytes(h[:8], "big"))
        return r

    def _latch_global_locked(self, f: FaultSpec) -> bool:
        """Once-per-JOB latch: a marker under <staging>/chaos/fired/ shared by
        every process and every gang attempt. True exactly once."""
        if not self._staging:
            return True  # no shared dir: in-process latch only
        path = os.path.join(
            self._staging, "chaos", "fired", hashlib.sha1(f.key.encode()).hexdigest()
        )
        try:
            os.makedirs(os.path.dirname(path), exist_ok=True)
            fd = os.open(path, os.O_CREAT | os.O_EXCL | os.O_WRONLY)
        except FileExistsError:
            return False
        except OSError:
            return True  # unwritable staging: degrade to the in-process latch
        with os.fdopen(fd, "w") as fh:
            fh.write(f"{self.identity} {int(time.time() * 1000)}\n")
        return True

    def _record_locked(self, f: FaultSpec, detail: dict[str, Any] | None) -> None:
        rec = {
            "ts_ms": int(time.time() * 1000),
            "identity": self.identity,
            "kind": f.kind,
            "fault": f.key,
        }
        if detail:
            rec.update(detail)
        self.injected.append(rec)
        _INJECTIONS.inc(kind=f.kind)
        # annotate the span this fault perturbs (e.g. rpc-drop fires inside
        # the open rpc.client span) so `tony trace` shows the injection on
        # the affected timeline slice; no-op when tracing is off
        obs_trace.add_event(f"chaos.{f.kind}", fault=f.key, identity=self.identity)
        if self._log_path:
            try:
                with open(self._log_path, "a") as fh:  # lint: disable=blocking-under-lock — chaos-injection log: leaf sink serializer on a fault-injection (test-only) path
                    fh.write(json.dumps(rec) + "\n")
            except OSError:
                pass

    # ------------------------------------------------------ rpc client seam
    def rpc_before_send(self, method: str, timeout_s: float) -> None:
        """Outbound-call faults, applied inside RpcClient.call's attempt loop.
        Raises ConnectionError/TimeoutError to simulate the failure."""
        f = self.take("rpc-delay", detail={"method": method})
        if f is not None:
            time.sleep(f.ms(default=200) / 1000)
        f = self.take("rpc-drop", detail={"method": method})
        if f is not None:
            raise ConnectionResetError(f"chaos rpc-drop: {method}")
        f = self.take("rpc-blackhole", detail={"method": method})
        if f is not None:
            time.sleep(min(f.ms(default=int(timeout_s * 1000)) / 1000, timeout_s))
            raise TimeoutError(f"chaos rpc-blackhole: {method}")

    def rpc_sever_after_send(self, method: str) -> bool:
        """True → the caller closes the socket after sending, losing the
        response mid-call (the server may have executed the method)."""
        return self.take("rpc-sever", detail={"method": method}) is not None

    # ------------------------------------------ resource-manager (AM) seam
    def poll_preempt_notice(self) -> "dict[str, Any] | None":
        """``preempt-drain`` fault at the AM's ``poll_preemption`` seam:
        synthesize the pool's COOPERATIVE drain notice (same shape the pool
        service piggybacks on ``poll_exited``), so a single-tenant run — the
        in-process RM, which never preempts — exercises the whole
        checkpoint-then-yield machinery: heartbeat fan-out, DrainCourier,
        urgent save / serving drain, cooperative yield. Fires once (the
        standard once-per-job latch); ``ms=`` sets the deadline."""
        f = self.take("preempt-drain")
        if f is None:
            return None
        return {
            "req_id": f"chaos-{f.key}",
            "mode": "drain",
            "deadline_ms": f.ms(default=20_000),
        }

    def perturb_container_exits(self, rm, exits: dict[str, int]) -> dict[str, int]:
        """node-loss / preempt faults applied at the RM's poll_exited seam:
        victims are killed through the real kill path and surface as synthetic
        exit codes, exactly as a dead node / pool preemption would."""
        live = rm._live_containers()
        if not live:
            return exits
        # fidelity: a preempted container / dead node gets NO drain grace —
        # and the graceful kill would block this (monitor-loop) caller for
        # the whole grace window per victim, letting survivors train seconds
        # past the fault. RMs without an abrupt path fall back to kill_container.
        kill = getattr(rm, "kill_container_abrupt", None) or rm.kill_container
        for f in self.schedule.of_kind("node-loss"):
            victims = [
                c for c in live
                if f.target is None or (c.job_type, c.task_index) == f.target
            ]
            if not victims:
                continue
            got = self.take_spec(f, detail={"containers": [c.id for c in victims]})
            if got is None:
                continue
            for c in victims:
                kill(c)
                exits.setdefault(c.id, constants.EXIT_NODE_LOST)
        for f in self.schedule.of_kind("preempt"):
            victims = [
                c for c in live
                if f.target is None or (c.job_type, c.task_index) == f.target
            ]
            if not victims:
                continue
            got = self.take_spec(f, detail={"containers": [c.id for c in victims]})
            if got is None:
                continue
            for c in victims:
                kill(c)
                exits.setdefault(c.id, constants.EXIT_PREEMPTED)
        return exits


def _parse_task(identity: str) -> tuple[str, int] | None:
    job, _, idx = identity.partition(":")
    if job and idx.isdigit():
        return (job, int(idx))
    return None
