"""Deterministic, seed-driven fault injection for the cluster control plane.

Chaos-engineering discipline (Basiri et al., IEEE Software 2016) applied to
the TonY recovery machinery: a ``FaultSchedule`` parsed from
``tony.chaos.spec`` drives seeded injection points wired into the real code
paths (rpc, executor, resource managers, checkpoint restore). Everything is a
no-op unless a schedule is configured. See docs/fault-tolerance.md.
"""

from tony_tpu.chaos.context import ChaosContext
from tony_tpu.chaos.inject import corrupt_latest_checkpoint, maybe_corrupt_checkpoint
from tony_tpu.chaos.schedule import CONTAINER_FAULTS, FAULT_KINDS, FaultSchedule, FaultSpec

__all__ = [
    "ChaosContext",
    "FaultSchedule",
    "FaultSpec",
    "FAULT_KINDS",
    "CONTAINER_FAULTS",
    "corrupt_latest_checkpoint",
    "maybe_corrupt_checkpoint",
]
